"""JobSupervisor: the detached actor that owns one submitted job.

Analogue of the reference `JobSupervisor`
(ref: dashboard/modules/job/job_manager.py:140 — a detached actor that
runs the entrypoint as a subprocess, polls it, and publishes terminal
status). Status + log tail live in GCS KV (namespace "job") so they
survive the supervisor itself.
"""
from __future__ import annotations

import json
import os
import subprocess
import tempfile
import threading
import time

JOB_KV_NAMESPACE = b"job"
LOG_TAIL_BYTES = 1 << 20  # keep at most 1 MiB of trailing output in KV


class JobSupervisor:
    """One instance per submitted job, named `_job_supervisor_<id>`."""

    def __init__(self, submission_id: str, entrypoint: str,
                 metadata: dict, gcs_address: str,
                 env_vars: dict | None = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.metadata = metadata or {}
        self.gcs_address = gcs_address
        self.env_vars = env_vars or {}
        self._proc: subprocess.Popen | None = None
        self._log_path = os.path.join(
            tempfile.gettempdir(), f"ray_tpu_job_{submission_id}.log")
        self._stopped = False
        self._start_time = time.time()
        self._write_status("PENDING", "")
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    # -- kv state -------------------------------------------------------
    def _kv(self):
        from ray_tpu.api import _global_worker

        return _global_worker()

    def _write_status(self, status: str, message: str) -> None:
        info = {
            "submission_id": self.submission_id,
            "entrypoint": self.entrypoint,
            "status": status,
            "message": message,
            "metadata": self.metadata,
            "start_time": self._start_time,
            "end_time": (time.time()
                         if status in ("SUCCEEDED", "FAILED", "STOPPED")
                         else None),
        }
        self._kv().kv_put(JOB_KV_NAMESPACE,
                          self.submission_id.encode(),
                          json.dumps(info).encode())

    def _flush_logs(self) -> None:
        try:
            with open(self._log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - LOG_TAIL_BYTES))
                tail = f.read()
        except OSError:
            tail = b""
        self._kv().kv_put(JOB_KV_NAMESPACE,
                          f"{self.submission_id}:logs".encode(), tail)

    # -- lifecycle ------------------------------------------------------
    def _run(self) -> None:
        env = dict(os.environ)
        env.update({k: str(v) for k, v in self.env_vars.items()})
        # The entrypoint's own ray_tpu.init() should join THIS cluster
        # (the reference sets RAY_ADDRESS the same way).
        env["RAY_TPU_ADDRESS"] = self.gcs_address
        env["RAY_TPU_JOB_SUBMISSION_ID"] = self.submission_id
        with open(self._log_path, "wb") as logf:
            self._proc = subprocess.Popen(
                self.entrypoint, shell=True, env=env,
                stdout=logf, stderr=subprocess.STDOUT,
                start_new_session=True)
            self._write_status("RUNNING", "")
            while self._proc.poll() is None:
                time.sleep(0.5)
                self._flush_logs()
                # KV stop flag: lets HTTP-only clients (dashboard REST)
                # stop the job without an actor-call path into this
                # supervisor (ref: job_head.py stop → JobManager).
                if not self._stopped:
                    try:
                        flag = self._kv().kv_get(
                            JOB_KV_NAMESPACE,
                            f"{self.submission_id}:stop".encode())
                    except Exception:  # noqa: BLE001 GCS blip
                        flag = None
                    if flag:
                        # Consume the flag: a leftover would instantly
                        # kill a future job resubmitted under this id.
                        try:
                            self._kv().kv_del(
                                JOB_KV_NAMESPACE,
                                f"{self.submission_id}:stop".encode())
                        except Exception:  # noqa: BLE001
                            pass
                        self.stop()
        self._flush_logs()
        rc = self._proc.returncode
        if self._stopped:
            self._write_status("STOPPED", "stopped by user")
        elif rc == 0:
            self._write_status("SUCCEEDED", "")
        else:
            self._write_status(
                "FAILED", f"entrypoint exited with code {rc}")

    def ping(self) -> bool:
        return True

    def stop(self) -> bool:
        """SIGTERM the entrypoint's process group; SIGKILL after 3s."""
        if self._proc is None or self._proc.poll() is not None:
            return False
        self._stopped = True
        import signal

        try:
            os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

        def hard_kill():
            time.sleep(3)
            if self._proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass

        threading.Thread(target=hard_kill, daemon=True).start()
        return True

    def logs(self) -> bytes:
        try:
            with open(self._log_path, "rb") as f:
                return f.read()[-LOG_TAIL_BYTES:]
        except OSError:
            return b""
