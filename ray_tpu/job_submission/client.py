"""JobSubmissionClient: the user-facing job SDK.

Analogue of the reference client (ref: dashboard/modules/job/sdk.py:39
JobSubmissionClient — submit_job/get_job_status/get_job_logs/stop_job/
list_jobs/delete_job). The reference round-trips through the dashboard
REST API; ours supports BOTH transports: `address="http://host:port"`
speaks the dashboard REST API (submit/status/logs/stop/list — a
non-Python client needs nothing but HTTP, ref: job_head.py routes),
while a GCS address (or None) joins the cluster directly as a driver
and drives the detached JobSupervisor actor + GCS KV records.
"""
from __future__ import annotations

import dataclasses
import json
import time
import uuid
from typing import Dict, List, Optional

from ray_tpu.job_submission.supervisor import JOB_KV_NAMESPACE, JobSupervisor


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclasses.dataclass
class JobInfo:
    submission_id: str
    entrypoint: str
    status: str
    message: str = ""
    metadata: Dict[str, str] = dataclasses.field(default_factory=dict)
    start_time: Optional[float] = None
    end_time: Optional[float] = None


def parse_job_records(items: Dict[bytes, Optional[bytes]]) -> List[JobInfo]:
    """Decode raw KV entries from the "job" namespace into JobInfo rows.

    The single owner of the KV layout (sub-keys carry a ':' — e.g.
    '<id>:logs' — and are not job records); the CLI, dashboard, and
    client all list jobs through this."""
    out = []
    for key, raw in items.items():
        if b":" in key or raw is None:
            continue
        out.append(JobInfo(**json.loads(raw.decode())))
    return sorted(out, key=lambda j: j.start_time or 0)


class JobSubmissionClient:
    """Submit shell entrypoints to a cluster and track them.

    `address` is the GCS address ("host:port"); None uses/starts the
    ambient cluster via ray_tpu.init().
    """

    def __init__(self, address: Optional[str] = None):
        self._http: Optional[str] = None
        if address is not None and address.startswith(("http://",
                                                       "https://")):
            self._http = address.rstrip("/")
            self._worker = None
            return
        import ray_tpu

        if address is not None and not ray_tpu.is_initialized():
            ray_tpu.init(address=address, ignore_reinit_error=True)
        else:
            ray_tpu.init(ignore_reinit_error=True)
        from ray_tpu.api import _global_worker

        self._worker = _global_worker()
        if address is not None and self._worker.gcs_address != address:
            raise RuntimeError(
                f"this process is already connected to cluster "
                f"{self._worker.gcs_address}; cannot submit to {address} "
                f"(one cluster per process)")

    # -- http transport -------------------------------------------------
    def _http_req(self, method: str, path: str, body: Optional[dict] = None,
                  raw: bool = False):
        import urllib.error
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            f"{self._http}{path}", data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=120) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            if e.code == 404:
                raise RuntimeError(detail) from None
            raise RuntimeError(
                f"HTTP {e.code} from {path}: {detail}") from None
        if raw:
            return payload.decode(errors="replace")
        return json.loads(payload)

    # -- submission -----------------------------------------------------
    def submit_job(
        self,
        *,
        entrypoint: str,
        submission_id: Optional[str] = None,
        runtime_env: Optional[dict] = None,
        metadata: Optional[Dict[str, str]] = None,
        entrypoint_num_cpus: float = 0,
    ) -> str:
        if self._http is not None:
            out = self._http_req("POST", "/api/jobs", {
                "entrypoint": entrypoint,
                "submission_id": submission_id,
                "runtime_env": runtime_env,
                "metadata": metadata,
                "entrypoint_num_cpus": entrypoint_num_cpus,
            })
            return out["submission_id"]
        import ray_tpu

        submission_id = submission_id or f"raytpu_job_{uuid.uuid4().hex[:10]}"
        existing = self._get_info(submission_id)
        if existing is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        env_vars = {}
        if runtime_env and runtime_env.get("env_vars"):
            env_vars = dict(runtime_env["env_vars"])
            runtime_env = {k: v for k, v in runtime_env.items()
                           if k != "env_vars"}
        supervisor_cls = ray_tpu.remote(JobSupervisor)
        opts = {
            "name": f"_job_supervisor_{submission_id}",
            "namespace": "_job",
            "lifetime": "detached",
            "num_cpus": entrypoint_num_cpus,
        }
        if runtime_env:
            opts["runtime_env"] = runtime_env
        handle = supervisor_cls.options(**opts).remote(
            submission_id, entrypoint, metadata or {},
            self._worker.gcs_address, env_vars)
        # Surface constructor errors synchronously (bad runtime_env etc.).
        ray_tpu.get(handle.ping.remote(), timeout=120)
        return submission_id

    # -- state ----------------------------------------------------------
    def _get_info(self, submission_id: str) -> Optional[JobInfo]:
        if self._http is not None:
            try:
                d = self._http_req("GET", f"/api/jobs/{submission_id}")
            except RuntimeError:
                return None
            return JobInfo(**{k: d.get(k) for k in (
                "submission_id", "entrypoint", "status", "message",
                "metadata", "start_time", "end_time")})
        raw = self._worker.kv_get(JOB_KV_NAMESPACE, submission_id.encode())
        if raw is None:
            return None
        d = json.loads(raw.decode())
        return JobInfo(**d)

    def get_job_info(self, submission_id: str) -> JobInfo:
        info = self._get_info(submission_id)
        if info is None:
            raise RuntimeError(f"job {submission_id!r} does not exist")
        return info

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id).status

    def get_job_logs(self, submission_id: str) -> str:
        if self._http is not None:
            return self._http_req(
                "GET", f"/api/jobs/{submission_id}/logs", raw=True)
        import ray_tpu

        # Prefer the live supervisor (full log file); fall back to the KV
        # tail it flushed.
        try:
            actor = ray_tpu.get_actor(
                f"_job_supervisor_{submission_id}", namespace="_job")
            return ray_tpu.get(actor.logs.remote(),
                               timeout=30).decode(errors="replace")
        except Exception:  # noqa: BLE001
            raw = self._worker.kv_get(
                JOB_KV_NAMESPACE, f"{submission_id}:logs".encode())
            if raw is None:
                self.get_job_info(submission_id)  # raise if unknown job
                return ""
            return raw.decode(errors="replace")

    def list_jobs(self) -> List[JobInfo]:
        if self._http is not None:
            out = []
            for row in self._http_req("GET", "/api/jobs"):
                if row.get("kind") != "submission":
                    continue
                info = self._get_info(row["id"])
                if info is not None:
                    out.append(info)
            return out
        items = {key: self._worker.kv_get(JOB_KV_NAMESPACE, key)
                 for key in self._worker.kv_keys(JOB_KV_NAMESPACE, b"")}
        return parse_job_records(items)

    # -- control --------------------------------------------------------
    def stop_job(self, submission_id: str) -> bool:
        if self._http is not None:
            out = self._http_req(
                "POST", f"/api/jobs/{submission_id}/stop")
            return bool(out.get("stopped"))
        import ray_tpu

        self.get_job_info(submission_id)
        try:
            actor = ray_tpu.get_actor(
                f"_job_supervisor_{submission_id}", namespace="_job")
            return ray_tpu.get(actor.stop.remote(), timeout=30)
        except Exception:  # noqa: BLE001
            return False

    def delete_job(self, submission_id: str) -> bool:
        if self._http is not None:
            raise NotImplementedError(
                "delete_job needs a cluster connection (use the GCS "
                "address form of JobSubmissionClient)")
        info = self.get_job_info(submission_id)
        if info.status not in JobStatus.TERMINAL:
            raise RuntimeError(
                f"job {submission_id!r} is {info.status}; stop it first")
        self._worker.kv_del(JOB_KV_NAMESPACE, submission_id.encode())
        self._worker.kv_del(JOB_KV_NAMESPACE,
                            f"{submission_id}:logs".encode())
        self._worker.kv_del(JOB_KV_NAMESPACE,
                            f"{submission_id}:stop".encode())
        # Reap the (now idle) detached supervisor.
        import ray_tpu

        try:
            actor = ray_tpu.get_actor(
                f"_job_supervisor_{submission_id}", namespace="_job")
            ray_tpu.kill(actor)
        except Exception:  # noqa: BLE001
            pass
        return True

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> JobInfo:
        deadline = time.monotonic() + timeout
        while True:
            info = self.get_job_info(submission_id)
            if info.status in JobStatus.TERMINAL:
                return info
            if time.monotonic() >= deadline:
                raise TimeoutError(f"job {submission_id!r} still "
                                   f"{info.status} after {timeout}s")
            time.sleep(0.25)
