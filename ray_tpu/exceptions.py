"""Exception types raised by the runtime.

Mirrors the error taxonomy of the reference runtime
(ref: python/ray/exceptions.py) with a TPU-native runtime behind it.
"""
from __future__ import annotations

import traceback
from typing import Optional


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task or actor method.

    The original traceback is captured as text in the executing worker and
    re-raised at the `get()` call site (ref: python/ray/exceptions.py
    RayTaskError semantics).
    """

    def __init__(
        self,
        function_name: str = "<unknown>",
        traceback_str: str = "",
        cause: Optional[BaseException] = None,
        pid: int = 0,
        node_id: str = "",
    ):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        self.pid = pid
        self.node_id = node_id
        super().__init__(traceback_str or str(cause))

    @classmethod
    def from_exception(cls, exc: BaseException, function_name: str, pid: int = 0,
                       node_id: str = "") -> "TaskError":
        tb = "".join(
            traceback.format_exception(type(exc), exc, exc.__traceback__)
        )
        return cls(function_name=function_name, traceback_str=tb, cause=exc,
                   pid=pid, node_id=node_id)

    def __str__(self):
        return (
            f"Task '{self.function_name}' failed (pid={self.pid}, "
            f"node={self.node_id[:8]}):\n{self.traceback_str}"
        )

    def __reduce__(self):
        # Exception's default __reduce__ replays self.args into
        # __init__, which for this signature stuffs the formatted
        # message into function_name and DROPS every other field on
        # unpickle. The cause is deliberately omitted from the wire:
        # user exception types may not import on the other side (its
        # text already rides in traceback_str).
        return (type(self), (self.function_name, self.traceback_str,
                             None, self.pid, self.node_id))


class ActorError(TaskError):
    """An actor method invocation failed."""


class ActorDiedError(RayTpuError):
    """The actor backing a handle has died and will not be restarted."""

    def __init__(self, actor_id: str = "", reason: str = ""):
        self.actor_id = actor_id
        self.reason = reason
        super().__init__(f"Actor {actor_id[:8]} died: {reason}")

    def __reduce__(self):  # see TaskError.__reduce__
        return (type(self), (self.actor_id, self.reason))


class ActorUnavailableError(RayTpuError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ReplicaDrainingError(RayTpuError):
    """The serve replica is draining (downscale/redeploy) and no longer
    admits new requests.  Retry through the handle: routing excludes the
    draining replica after the next refresh.  Subclasses RayTpuError so
    the worker executor forwards it TYPED across the actor wire (see
    worker_main's RayTpuError passthrough) — callers catch it by type."""

    def __init__(self, replica_id: str = ""):
        self.replica_id = replica_id
        super().__init__(f"replica {replica_id!r} is draining; "
                         f"re-route this request")

    def __reduce__(self):  # see TaskError.__reduce__
        return (type(self), (self.replica_id,))


class KVMigrationError(RayTpuError):
    """A live KV migration (serve/disagg.py) could not be applied on the
    target replica — missing/stale ticket, frame-shape mismatch, or an
    exhausted block pool.  Callers treat it as "fall back to recompute":
    the resumed stream replays the context as an extended prompt instead
    of adopting shipped blocks.  Wire-typed (lossless __reduce__) so the
    fallback decision survives the actor boundary."""

    def __init__(self, request_id: str = "", reason: str = ""):
        self.request_id = request_id
        self.reason = reason
        super().__init__(f"KV migration failed for request "
                         f"{request_id!r}: {reason or 'unknown'}")

    def __reduce__(self):  # see TaskError.__reduce__
        return (type(self), (self.request_id, self.reason))


class TaskCancelledError(RayTpuError):
    """The task was cancelled before or during execution."""


class ObjectLostError(RayTpuError):
    """An object was evicted/lost and could not be reconstructed."""

    def __init__(self, object_id: str = "", message: str = ""):
        self.object_id = object_id
        super().__init__(message or f"Object {object_id[:8]} was lost.")

    def __reduce__(self):  # see TaskError.__reduce__
        return (type(self), (self.object_id, str(self)))


class ObjectReconstructionFailedError(ObjectLostError):
    """Lineage reconstruction of a lost object failed."""


class OwnerDiedError(ObjectLostError):
    """The owner (submitting worker) of an object died; value unrecoverable."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get(..., timeout=)` expired before the object was ready."""


class NodeDiedError(RayTpuError):
    """A node (daemon) died while hosting tasks/objects."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing a task died unexpectedly."""


class RuntimeEnvSetupError(RayTpuError):
    """Creating the runtime environment for a task/actor failed."""


class OutOfMemoryError(RayTpuError):
    """Worker killed by the memory monitor."""


class PlacementGroupUnavailableError(RayTpuError):
    """Placement group cannot be scheduled with current cluster resources."""


class PendingCallsLimitExceededError(RayTpuError):
    """Backpressure: actor's pending call queue is full."""


class CrossLanguageError(RayTpuError):
    """Error crossing a language boundary."""


class ChannelError(RayTpuError):
    """Compiled-graph channel read/write failure."""


class ChannelTimeoutError(ChannelError, TimeoutError):
    """Compiled-graph channel read/write timed out."""


class DataPlaneError(RayTpuError):
    """A streaming Dataset pipeline (data/streaming) failed in a way the
    operator graph cannot retry internally — an operator task raised on
    every attempt, a shuffle bundle was lost with its producer, or the
    split coordinator died mid-epoch.  Carries the operator name so the
    consumer-side traceback points at the stage, not the iterator.
    Wire-typed (lossless __reduce__): it crosses the coordinator ->
    consumer and worker -> driver wires."""

    def __init__(self, message: str = "", operator: str = ""):
        self.operator = operator
        super().__init__(message or f"data plane failure in operator "
                         f"{operator!r}")

    def __reduce__(self):  # see TaskError.__reduce__
        return (type(self),
                (self.args[0] if self.args else "", self.operator))


class BackpressureTimeout(DataPlaneError, TimeoutError):
    """A byte-stalled operator made no forward progress for
    ``data_stream_stall_timeout_s`` — every downstream consumer stopped
    pulling (deadlocked sink, wedged trainer) while the operator sat at
    its in-flight byte cap.  Raising beats stalling forever: the stall
    seconds already accrued are in Dataset.stats().  Subclasses
    TimeoutError so generic timeout handlers also catch it."""

    def __init__(self, message: str = "", operator: str = "",
                 waited_s: float = 0.0, inflight_bytes: int = 0):
        self.waited_s = waited_s
        self.inflight_bytes = inflight_bytes
        super().__init__(
            message or (
                f"operator {operator!r} backpressured for "
                f"{waited_s:.1f}s with {inflight_bytes} bytes in flight "
                f"and no downstream progress"
            ),
            operator,
        )

    def __reduce__(self):
        return (type(self), (self.args[0] if self.args else "",
                             self.operator, self.waited_s,
                             self.inflight_bytes))


class StreamQueueFullError(RayTpuError):
    """A serve streaming consumer fell ``serve_stream_queue_max`` tokens
    behind and its stream was dropped (backpressure instead of unbounded
    replica RSS growth).  Crosses the replica -> proxy wire, so it lives
    in the typed tree and round-trips pickle with its bound intact."""

    def __init__(self, message: str = "", queue_max: int = 0):
        super().__init__(message)
        self.queue_max = queue_max

    def __reduce__(self):
        return (type(self),
                (self.args[0] if self.args else "", self.queue_max))
