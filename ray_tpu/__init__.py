"""ray_tpu: a TPU-native distributed compute framework.

Task/actor core runtime with a shared-memory object store and
topology-aware gang scheduling; JAX/XLA/pjit as the intra-slice parallelism
substrate; libraries for data pipelines, distributed training, hyperparameter
tuning, online serving, and RL — the capability surface of the reference
(astron8t-voyagerx/ray) redesigned TPU-first.
"""
from ray_tpu._version import version as __version__
from ray_tpu.api import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    register_cross_lang,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.streaming import ObjectRefGenerator
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context
from ray_tpu import exceptions

_SUBPACKAGES = ("data", "train", "tune", "serve", "dag", "util", "parallel",
                "ops", "models", "workflow", "rllib", "autoscaler",
                "job_submission", "dashboard", "experimental")


def __getattr__(name):
    """Lazy subpackage access: `ray_tpu.data`, `ray_tpu.train`, ... import
    on first touch (keeps bare `import ray_tpu` light)."""
    if name in _SUBPACKAGES:
        import importlib

        try:
            mod = importlib.import_module(f"ray_tpu.{name}")
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"module 'ray_tpu' has no attribute {name!r}") from e
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "cluster_resources",
    "available_resources",
    "nodes",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorClass",
    "ActorHandle",
    "method",
    "RemoteFunction",
    "get_runtime_context",
    "exceptions",
]
