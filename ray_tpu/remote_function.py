"""`@remote` function wrapper.

Analogue of the reference RemoteFunction (ref: python/ray/remote_function.py;
`_remote` at :266 resolves options and submits through the core worker).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Union

from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import TaskOptions


def _merge_options(base: TaskOptions, **updates) -> TaskOptions:
    known = {f.name for f in dataclasses.fields(TaskOptions)}
    clean: Dict[str, Any] = {}
    for k, v in updates.items():
        if k not in known:
            raise ValueError(f"Unknown option '{k}'")
        clean[k] = v
    return dataclasses.replace(base, **clean)


class RemoteFunction:
    def __init__(self, func, options: Optional[TaskOptions] = None):
        self._function = func
        self._options = options or TaskOptions()
        functools.update_wrapper(self, func)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function.__qualname__}' cannot be called "
            "directly. Use '.remote(...)' instead."
        )

    def options(self, **updates) -> "RemoteFunction":
        return RemoteFunction(self._function,
                              _merge_options(self._options, **updates))

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        from ray_tpu.api import _global_worker

        worker = _global_worker()
        if self._options.num_returns == "streaming":
            # Generator task: yields become refs consumable before the
            # task finishes (ref: ObjectRefGenerator, _raylet.pyx:272).
            return worker.submit_streaming_task(
                self._function, list(args), dict(kwargs), self._options)
        refs = worker.submit_task(self._function, list(args), dict(kwargs),
                                  self._options)
        if self._options.num_returns == 1:
            return refs[0]
        return refs

    @property
    def bind(self):
        """Build a lazy DAG node (ref: python/ray/dag/dag_node.py)."""
        from ray_tpu.dag.api import function_bind

        return functools.partial(function_bind, self)
