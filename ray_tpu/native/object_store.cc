// Shared-memory object store: the TPU-native plasma equivalent.
//
// Role parity with the reference's plasma store
// (ref: src/ray/object_manager/plasma/store.h:55, object_lifecycle_manager.h,
// eviction_policy.h, plasma_allocator.h) with a different, simpler design
// suited to a per-host daemonless data plane:
//
//   * One directory on tmpfs (/dev/shm) per node; one file per object.
//     Writers create `<id>.building`, fill it, then atomically rename to
//     `<id>` on seal — readers can only ever observe sealed objects.
//   * A control region (`.index` file) mmap'd into every client holds an
//     open-addressing hash table of slots with process-shared atomics:
//     state, refcount, size, LRU clock. A robust process-shared mutex
//     guards structural changes; a crashed holder is recovered via
//     EOWNERDEAD.
//   * Zero-copy reads: clients mmap the object file read-only; numpy/arrow
//     buffers alias the mapping directly.
//   * LRU eviction of sealed, refcount-0 objects when capacity is exceeded
//     (ref behavior: plasma LRU eviction + fallback allocation); spill to a
//     disk directory is handled a level up by the node daemon.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055'53544F52ULL;  // "RTPUSTOR"
constexpr uint32_t kIdSize = 20;

enum SlotState : uint32_t {
  kEmpty = 0,
  kCreating = 1,
  kSealed = 2,
  kTombstone = 3,
};

struct Slot {
  uint8_t id[kIdSize];
  std::atomic<uint32_t> state;
  std::atomic<uint32_t> refcount;
  std::atomic<uint64_t> size;
  std::atomic<uint64_t> lru_tick;
  std::atomic<uint64_t> create_ts;  // unix seconds; stale-kCreating reclaim
};

// A writer that died between create and seal/abort leaves kCreating forever;
// reclaim such slots after this many seconds.
constexpr uint64_t kStaleCreatingSecs = 300;

// Warm-file recycle pool: deleting/evicting a LARGE object parks its tmpfs
// file here (pages stay resident and faulted-in) instead of unlinking it;
// the next large create claims a matching file via rename and writes onto
// already-warm pages.  Fresh tmpfs page allocation is the dominant cost of
// a large create (~1.3 GB/s fault-bound vs ~6 GB/s rewriting warm pages on
// this class of host), so steady-state put/transfer traffic that cycles
// similar sizes runs at warm-page speed.  The pool is bounded (entry count
// and a byte cap derived from capacity) and its bytes count toward the
// store's tmpfs footprint, so eviction drains it before touching live
// objects.
constexpr uint32_t kRecycleSlots = 64;
constexpr uint64_t kRecycleMinBytes = 1ULL << 20;  // only pool files >= 1 MiB

struct RecycleEntry {
  std::atomic<uint64_t> size;  // 0 = empty
  std::atomic<uint32_t> seq;   // names the file: .recycle.<idx>.<seq>
  uint32_t pad;
};

struct IndexHeader {
  uint64_t magic;
  uint64_t capacity;
  uint64_t num_slots;
  std::atomic<uint64_t> used;
  std::atomic<uint64_t> clock;
  std::atomic<uint64_t> num_objects;
  std::atomic<uint64_t> recycle_bytes;
  std::atomic<uint32_t> recycle_seq;
  uint32_t pad0;
  RecycleEntry recycle[kRecycleSlots];
  pthread_mutex_t mutex;  // robust, process-shared
};

struct Store {
  char dir[4096];
  IndexHeader* hdr;
  Slot* slots;
  size_t index_bytes;
};

uint64_t HashId(const uint8_t* id) {
  // FNV-1a over the 20-byte id.
  uint64_t h = 1469598103934665603ULL;
  for (uint32_t i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

void IdToHex(const uint8_t* id, char* out) {
  static const char* hex = "0123456789abcdef";
  for (uint32_t i = 0; i < kIdSize; i++) {
    out[2 * i] = hex[id[i] >> 4];
    out[2 * i + 1] = hex[id[i] & 0xf];
  }
  out[2 * kIdSize] = '\0';
}

void ObjectPath(const Store* s, const uint8_t* id, bool building, char* out,
                size_t outlen) {
  char hexid[2 * kIdSize + 1];
  IdToHex(id, hexid);
  snprintf(out, outlen, "%s/%s%s", s->dir, hexid, building ? ".building" : "");
}

void RecyclePath(const Store* s, uint32_t idx, uint32_t seq, char* out,
                 size_t outlen) {
  snprintf(out, outlen, "%s/.recycle.%u.%u", s->dir, idx, seq);
}

uint64_t RecycleCap(const Store* s) { return s->hdr->capacity / 8; }

// Unlink pooled files until `need` bytes are freed (UINT64_MAX = drain all).
// Caller holds the index lock.  Returns bytes freed.
uint64_t DrainRecycleLocked(Store* s, uint64_t need) {
  uint64_t freed = 0;
  for (uint32_t i = 0; i < kRecycleSlots && freed < need; i++) {
    RecycleEntry* e = &s->hdr->recycle[i];
    uint64_t sz = e->size.load(std::memory_order_acquire);
    if (sz == 0) continue;
    char path[4300];
    RecyclePath(s, i, e->seq.load(), path, sizeof(path));
    e->size.store(0, std::memory_order_release);
    s->hdr->recycle_bytes.fetch_sub(sz);
    unlink(path);
    freed += sz;
  }
  return freed;
}

// Try to park a sealed object's file in the recycle pool instead of
// unlinking it.  Caller holds the index lock.  Returns true when the file
// was renamed into the pool (caller must NOT unlink it).
bool TryRecycleLocked(Store* s, const uint8_t* id, uint64_t size) {
  if (size < kRecycleMinBytes) return false;
  if (s->hdr->recycle_bytes.load() + size > RecycleCap(s)) return false;
  for (uint32_t i = 0; i < kRecycleSlots; i++) {
    RecycleEntry* e = &s->hdr->recycle[i];
    if (e->size.load(std::memory_order_acquire) != 0) continue;
    uint32_t seq = s->hdr->recycle_seq.fetch_add(1);
    char src[4300], dst[4300];
    ObjectPath(s, id, /*building=*/false, src, sizeof(src));
    RecyclePath(s, i, seq, dst, sizeof(dst));
    if (rename(src, dst) != 0) return false;
    e->seq.store(seq);
    e->size.store(size, std::memory_order_release);
    s->hdr->recycle_bytes.fetch_add(size);
    return true;
  }
  return false;
}

int LockIndex(Store* s) {
  int rc = pthread_mutex_lock(&s->hdr->mutex);
  if (rc == EOWNERDEAD) {
    // Previous holder died mid-update; the table is slot-atomic so marking
    // consistent is safe.
    pthread_mutex_consistent(&s->hdr->mutex);
    rc = 0;
  }
  return rc;
}

void UnlockIndex(Store* s) { pthread_mutex_unlock(&s->hdr->mutex); }

// Find the slot for `id`, or (if absent and want_insert) an empty slot.
// Caller holds the index lock for inserts.
Slot* FindSlot(Store* s, const uint8_t* id, bool want_insert) {
  uint64_t n = s->hdr->num_slots;
  uint64_t idx = HashId(id) % n;
  Slot* first_free = nullptr;
  for (uint64_t probe = 0; probe < n; probe++) {
    Slot* slot = &s->slots[(idx + probe) % n];
    uint32_t st = slot->state.load(std::memory_order_acquire);
    if (st == kEmpty) {
      if (want_insert) return first_free ? first_free : slot;
      return nullptr;
    }
    if (st == kTombstone) {
      if (first_free == nullptr) first_free = slot;
      continue;
    }
    if (memcmp(slot->id, id, kIdSize) == 0) return slot;
  }
  return first_free;  // table full (or nullptr)
}

}  // namespace

extern "C" {

int rts_release(void* handle, const uint8_t* id);

// Error codes
enum {
  RTS_OK = 0,
  RTS_ERR_IO = -1,
  RTS_ERR_EXISTS = -2,
  RTS_ERR_NOT_FOUND = -3,
  RTS_ERR_FULL = -4,
  RTS_ERR_STATE = -5,
};

// Connect to (creating if needed) the store rooted at `dir`.
void* rts_connect(const char* dir, uint64_t capacity, uint64_t num_slots) {
  if (num_slots == 0) num_slots = 65536;
  Store* s = new Store();
  snprintf(s->dir, sizeof(s->dir), "%s", dir);
  mkdir(dir, 0777);

  char index_path[4200];
  snprintf(index_path, sizeof(index_path), "%s/.index", dir);
  s->index_bytes = sizeof(IndexHeader) + num_slots * sizeof(Slot);

  int fd = open(index_path, O_RDWR | O_CREAT | O_EXCL, 0666);
  bool creator = fd >= 0;
  if (!creator) {
    if (errno != EEXIST) {
      delete s;
      return nullptr;
    }
    fd = open(index_path, O_RDWR);
    if (fd < 0) {
      delete s;
      return nullptr;
    }
    // Joiners must use the creator's num_slots (a mismatched caller value
    // would map the wrong size and read past the mapping). Wait for init
    // (magic set last), then read the header.
    struct stat st;
    for (int i = 0; i < 10000; i++) {
      if (fstat(fd, &st) == 0 &&
          (size_t)st.st_size >= sizeof(IndexHeader))
        break;
      usleep(1000);
    }
    void* hdr_mem = mmap(nullptr, sizeof(IndexHeader),
                         PROT_READ, MAP_SHARED, fd, 0);
    if (hdr_mem == MAP_FAILED) {
      close(fd);
      delete s;
      return nullptr;
    }
    IndexHeader* hdr = reinterpret_cast<IndexHeader*>(hdr_mem);
    bool ready = false;
    for (int i = 0; i < 10000; i++) {
      if (hdr->magic == kMagic) {
        ready = true;
        break;
      }
      usleep(1000);
    }
    num_slots = ready ? hdr->num_slots : 0;
    munmap(hdr_mem, sizeof(IndexHeader));
    if (!ready) {
      close(fd);
      delete s;
      return nullptr;
    }
    s->index_bytes = sizeof(IndexHeader) + num_slots * sizeof(Slot);
  } else {
    if (ftruncate(fd, s->index_bytes) != 0) {
      close(fd);
      unlink(index_path);
      delete s;
      return nullptr;
    }
  }

  void* mem = mmap(nullptr, s->index_bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                   fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    delete s;
    return nullptr;
  }
  s->hdr = reinterpret_cast<IndexHeader*>(mem);
  s->slots = reinterpret_cast<Slot*>(reinterpret_cast<char*>(mem) +
                                     sizeof(IndexHeader));

  if (creator) {
    s->hdr->capacity = capacity;
    s->hdr->num_slots = num_slots;
    s->hdr->used.store(0);
    s->hdr->clock.store(1);
    s->hdr->num_objects.store(0);
    pthread_mutexattr_t attr;
    pthread_mutexattr_init(&attr);
    pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&s->hdr->mutex, &attr);
    pthread_mutexattr_destroy(&attr);
    std::atomic_thread_fence(std::memory_order_release);
    s->hdr->magic = kMagic;
  } else {
    for (int i = 0; i < 10000 && s->hdr->magic != kMagic; i++) usleep(1000);
    if (s->hdr->magic != kMagic) {
      munmap(mem, s->index_bytes);
      delete s;
      return nullptr;
    }
  }
  return s;
}

void rts_disconnect(void* handle) {
  Store* s = static_cast<Store*>(handle);
  if (s == nullptr) return;
  munmap(s->hdr, s->index_bytes);
  delete s;
}

uint64_t rts_capacity(void* handle) {
  return static_cast<Store*>(handle)->hdr->capacity;
}

uint64_t rts_used(void* handle) {
  return static_cast<Store*>(handle)->hdr->used.load();
}

uint64_t rts_num_objects(void* handle) {
  return static_cast<Store*>(handle)->hdr->num_objects.load();
}

// Evict up to `bytes_needed` of sealed, unreferenced objects (LRU order).
// Returns bytes actually freed. Caller must NOT hold the lock.
uint64_t rts_evict(void* handle, uint64_t bytes_needed) {
  Store* s = static_cast<Store*>(handle);
  uint64_t freed = 0;
  if (LockIndex(s) != 0) return 0;
  // Pooled warm files are the cheapest bytes to give back: no live object
  // dies when they go.
  freed += DrainRecycleLocked(s, bytes_needed);
  // Reclaim slots orphaned in kCreating by a crashed writer.
  uint64_t now = (uint64_t)time(nullptr);
  for (uint64_t i = 0; i < s->hdr->num_slots; i++) {
    Slot* slot = &s->slots[i];
    if (slot->state.load() == kCreating &&
        now > slot->create_ts.load() + kStaleCreatingSecs) {
      char path[4300];
      ObjectPath(s, slot->id, /*building=*/true, path, sizeof(path));
      unlink(path);
      s->hdr->used.fetch_sub(slot->size.load());
      s->hdr->num_objects.fetch_sub(1);
      slot->state.store(kTombstone);
      freed += slot->size.load();
    }
  }
  while (freed < bytes_needed) {
    Slot* victim = nullptr;
    uint64_t best_tick = UINT64_MAX;
    for (uint64_t i = 0; i < s->hdr->num_slots; i++) {
      Slot* slot = &s->slots[i];
      if (slot->state.load() == kSealed && slot->refcount.load() == 0) {
        uint64_t tick = slot->lru_tick.load();
        if (tick < best_tick) {
          best_tick = tick;
          victim = slot;
        }
      }
    }
    if (victim == nullptr) break;
    char path[4300];
    ObjectPath(s, victim->id, false, path, sizeof(path));
    unlink(path);
    uint64_t sz = victim->size.load();
    victim->state.store(kTombstone, std::memory_order_release);
    s->hdr->used.fetch_sub(sz);
    s->hdr->num_objects.fetch_sub(1);
    freed += sz;
  }
  UnlockIndex(s);
  return freed;
}

// Create a new object of `size` bytes. On success returns RTS_OK and sets
// *fd_out to a writable fd (caller mmaps and must close). Evicts LRU
// objects if needed.
int rts_create(void* handle, const uint8_t* id, uint64_t size, int* fd_out) {
  Store* s = static_cast<Store*>(handle);
  if (LockIndex(s) != 0) return RTS_ERR_IO;
  // Capacity check + eviction, decided under the lock so concurrent
  // creators cannot both pass and oversubscribe tmpfs.  Pooled warm files
  // count toward the footprint (their pages are still resident) and are
  // drained before any live object is evicted.
  if (s->hdr->used.load() + s->hdr->recycle_bytes.load() + size >
      s->hdr->capacity) {
    uint64_t need = s->hdr->used.load() + s->hdr->recycle_bytes.load() +
                    size - s->hdr->capacity;
    uint64_t drained = DrainRecycleLocked(s, need);
    if (drained < need) {
      UnlockIndex(s);
      rts_evict(handle, need - drained);
      if (LockIndex(s) != 0) return RTS_ERR_IO;
    }
    if (s->hdr->used.load() + s->hdr->recycle_bytes.load() + size >
        s->hdr->capacity) {
      UnlockIndex(s);
      return RTS_ERR_FULL;
    }
  }
  Slot* slot = FindSlot(s, id, /*want_insert=*/true);
  if (slot == nullptr) {
    UnlockIndex(s);
    return RTS_ERR_FULL;
  }
  uint32_t st = slot->state.load();
  if (st == kCreating || st == kSealed) {
    UnlockIndex(s);
    return RTS_ERR_EXISTS;
  }
  memcpy(slot->id, id, kIdSize);
  slot->refcount.store(0);
  slot->size.store(size);
  slot->lru_tick.store(s->hdr->clock.fetch_add(1));
  slot->create_ts.store((uint64_t)time(nullptr));
  slot->state.store(kCreating, std::memory_order_release);
  s->hdr->used.fetch_add(size);
  s->hdr->num_objects.fetch_add(1);
  // Claim a pooled warm file of a compatible size (>= requested, bounded
  // waste) while still under the lock; the rename happens after unlock —
  // the claimed entry is already ours (size zeroed), so no racer can touch
  // the file.
  uint64_t reuse_sz = 0;
  char reuse_path[4300];
  if (size >= kRecycleMinBytes) {
    for (uint32_t i = 0; i < kRecycleSlots; i++) {
      RecycleEntry* e = &s->hdr->recycle[i];
      uint64_t rsz = e->size.load(std::memory_order_acquire);
      if (rsz >= size && rsz <= 2 * size) {
        RecyclePath(s, i, e->seq.load(), reuse_path, sizeof(reuse_path));
        e->size.store(0, std::memory_order_release);
        s->hdr->recycle_bytes.fetch_sub(rsz);
        reuse_sz = rsz;
        break;
      }
    }
  }
  UnlockIndex(s);

  char path[4300];
  ObjectPath(s, id, /*building=*/true, path, sizeof(path));
  int fd = -1;
  if (reuse_sz > 0) {
    if (rename(reuse_path, path) == 0) {
      fd = open(path, O_RDWR);
      if (fd >= 0 && reuse_sz != size && ftruncate(fd, size) != 0) {
        close(fd);
        fd = -1;
      }
    } else {
      unlink(reuse_path);  // claimed but unusable; don't leak the file
    }
  }
  if (fd < 0) {
    fd = open(path, O_RDWR | O_CREAT | O_TRUNC, 0666);
    if (fd >= 0 && size > 0 && ftruncate(fd, size) != 0) {
      close(fd);
      fd = -1;
    }
  }
  if (fd < 0) {
    unlink(path);
    LockIndex(s);
    slot->state.store(kTombstone);
    s->hdr->used.fetch_sub(size);
    s->hdr->num_objects.fetch_sub(1);
    UnlockIndex(s);
    return RTS_ERR_IO;
  }
  *fd_out = fd;
  return RTS_OK;
}

// Seal a created object: atomic rename makes it visible to readers.
int rts_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (LockIndex(s) != 0) return RTS_ERR_IO;
  Slot* slot = FindSlot(s, id, false);
  if (slot == nullptr || slot->state.load() != kCreating) {
    UnlockIndex(s);
    return slot == nullptr ? RTS_ERR_NOT_FOUND : RTS_ERR_STATE;
  }
  char src[4300], dst[4300];
  ObjectPath(s, id, true, src, sizeof(src));
  ObjectPath(s, id, false, dst, sizeof(dst));
  if (rename(src, dst) != 0) {
    UnlockIndex(s);
    return RTS_ERR_IO;
  }
  slot->state.store(kSealed, std::memory_order_release);
  UnlockIndex(s);
  return RTS_OK;
}

// Abort a create-in-progress (e.g. writer failed).
int rts_abort(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (LockIndex(s) != 0) return RTS_ERR_IO;
  Slot* slot = FindSlot(s, id, false);
  if (slot == nullptr || slot->state.load() != kCreating) {
    UnlockIndex(s);
    return slot == nullptr ? RTS_ERR_NOT_FOUND : RTS_ERR_STATE;
  }
  char path[4300];
  ObjectPath(s, id, true, path, sizeof(path));
  unlink(path);
  slot->state.store(kTombstone);
  s->hdr->used.fetch_sub(slot->size.load());
  s->hdr->num_objects.fetch_sub(1);
  UnlockIndex(s);
  return RTS_OK;
}

// Get a sealed object: increments refcount, returns size and a read-only fd.
// The incref happens under the index lock so it cannot race an evictor that
// has already sampled refcount==0 (a lock-free incref could otherwise leave a
// stale release corrupting a recreated object's refcount).
int rts_get(void* handle, const uint8_t* id, uint64_t* size_out, int* fd_out) {
  Store* s = static_cast<Store*>(handle);
  if (LockIndex(s) != 0) return RTS_ERR_IO;
  Slot* slot = FindSlot(s, id, false);
  if (slot == nullptr ||
      slot->state.load(std::memory_order_acquire) != kSealed) {
    UnlockIndex(s);
    return RTS_ERR_NOT_FOUND;
  }
  slot->refcount.fetch_add(1);
  slot->lru_tick.store(s->hdr->clock.fetch_add(1));
  UnlockIndex(s);
  char path[4300];
  ObjectPath(s, id, false, path, sizeof(path));
  int fd = open(path, O_RDONLY);
  if (fd < 0) {
    rts_release(handle, id);
    return RTS_ERR_IO;
  }
  *size_out = slot->size.load();
  *fd_out = fd;
  return RTS_OK;
}

// Release a get() reference.
int rts_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  if (LockIndex(s) != 0) return RTS_ERR_IO;
  Slot* slot = FindSlot(s, id, false);
  if (slot == nullptr || slot->state.load() != kSealed ||
      memcmp(slot->id, id, kIdSize) != 0) {
    UnlockIndex(s);
    return RTS_ERR_NOT_FOUND;
  }
  slot->refcount.fetch_sub(1);
  UnlockIndex(s);
  return RTS_OK;
}

// Introspect a slot without touching refcounts or the LRU clock:
// state/size/refcount out-params. Backs the transfer-plane leak
// assertions (a sealed object whose transfer finished must be back at
// refcount 0) and lets the daemon observe create-then-fill progress.
// Returns RTS_OK, or RTS_ERR_NOT_FOUND for empty/tombstoned slots.
int rts_stat(void* handle, const uint8_t* id, uint32_t* state_out,
             uint64_t* size_out, uint32_t* refcount_out) {
  Store* s = static_cast<Store*>(handle);
  if (LockIndex(s) != 0) return RTS_ERR_IO;
  Slot* slot = FindSlot(s, id, false);
  if (slot == nullptr) {
    UnlockIndex(s);
    return RTS_ERR_NOT_FOUND;
  }
  uint32_t st = slot->state.load(std::memory_order_acquire);
  if (st == kEmpty || st == kTombstone) {
    UnlockIndex(s);
    return RTS_ERR_NOT_FOUND;
  }
  *state_out = st;
  *size_out = slot->size.load();
  *refcount_out = slot->refcount.load();
  UnlockIndex(s);
  return RTS_OK;
}

int rts_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Slot* slot = FindSlot(s, id, false);
  return (slot != nullptr &&
          slot->state.load(std::memory_order_acquire) == kSealed)
             ? 1
             : 0;
}

// Delete a sealed object regardless of LRU position (refcount must be 0
// unless force). Used by the owner's distributed GC.
int rts_delete(void* handle, const uint8_t* id, int force) {
  Store* s = static_cast<Store*>(handle);
  if (LockIndex(s) != 0) return RTS_ERR_IO;
  Slot* slot = FindSlot(s, id, false);
  if (slot == nullptr || slot->state.load() != kSealed) {
    UnlockIndex(s);
    return RTS_ERR_NOT_FOUND;
  }
  if (!force && slot->refcount.load() != 0) {
    UnlockIndex(s);
    return RTS_ERR_STATE;
  }
  uint64_t sz = slot->size.load();
  // Explicit GC delete is the steady-state recycling point: large files
  // park in the warm pool for the next large create instead of unlinking.
  if (!TryRecycleLocked(s, id, sz)) {
    char path[4300];
    ObjectPath(s, id, false, path, sizeof(path));
    unlink(path);
  }
  s->hdr->used.fetch_sub(sz);
  s->hdr->num_objects.fetch_sub(1);
  slot->state.store(kTombstone);
  UnlockIndex(s);
  return RTS_OK;
}

// Bytes held by the warm-file recycle pool (introspection: the pool is
// tmpfs footprint but neither `used` nor an object — the quiescence leak
// guard asserts it stays bounded).
uint64_t rts_recycle_bytes(void* handle) {
  return static_cast<Store*>(handle)->hdr->recycle_bytes.load();
}

// List up to `max` sealed object ids into out (max * 20 bytes). Returns count.
uint64_t rts_list(void* handle, uint8_t* out, uint64_t max) {
  Store* s = static_cast<Store*>(handle);
  uint64_t count = 0;
  for (uint64_t i = 0; i < s->hdr->num_slots && count < max; i++) {
    Slot* slot = &s->slots[i];
    if (slot->state.load(std::memory_order_acquire) == kSealed) {
      memcpy(out + count * kIdSize, slot->id, kIdSize);
      count++;
    }
  }
  return count;
}

// Destroy the store: unlink every object file and the index.
int rts_destroy(const char* dir) {
  char index_path[4200];
  snprintf(index_path, sizeof(index_path), "%s/.index", dir);
  unlink(index_path);
  return 0;
}

}  // extern "C"
