"""Build the native library (g++ → .so), caching by source mtime.

The reference builds its native substrate with bazel (ref: BUILD.bazel);
here a single translation unit compiled on demand keeps the loop tight. A
CMakeLists.txt is provided for standalone builds too.
"""
from __future__ import annotations

import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_NATIVE_DIR, "object_store.cc")
_OUT_DIR = os.path.join(_NATIVE_DIR, "_build")
_LIB = os.path.join(_OUT_DIR, "libray_tpu_store.so")
_lock = threading.Lock()


def library_path() -> str:
    """Return the path to the built library, building if stale/missing."""
    with _lock:
        if (not os.path.exists(_LIB)
                or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
            os.makedirs(_OUT_DIR, exist_ok=True)
            tmp = _LIB + ".tmp"
            cmd = [
                "g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC",
                "-Wall", "-o", tmp, _SRC, "-lpthread",
            ]
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            os.replace(tmp, _LIB)
    return _LIB
