"""Build the native library (g++ → .so), caching by source mtime.

The reference builds its native substrate with bazel (ref: BUILD.bazel);
here a single translation unit compiled on demand keeps the loop tight. A
CMakeLists.txt is provided for standalone builds too.
"""
from __future__ import annotations

import os
import subprocess
import threading

_NATIVE_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_NATIVE_DIR, "object_store.cc")
_OUT_DIR = os.path.join(_NATIVE_DIR, "_build")
_LIB = os.path.join(_OUT_DIR, "libray_tpu_store.so")
_lock = threading.Lock()


def library_path() -> str:
    """Return the path to the built library, building if stale/missing.

    Cross-process safe: concurrent workers serialize on an flock and use
    per-pid temp names so a half-written .so is never published."""
    from ray_tpu.core.config import get_config

    override = get_config().store_lib
    if override:
        # Instrumented builds (TSAN/ASAN via cmake -DSANITIZE=...) run
        # the python suite against their own .so.
        return override
    with _lock:
        if (os.path.exists(_LIB)
                and os.path.getmtime(_LIB) >= os.path.getmtime(_SRC)):
            return _LIB
        os.makedirs(_OUT_DIR, exist_ok=True)
        import fcntl

        with open(os.path.join(_OUT_DIR, ".build.lock"), "w") as lockf:
            fcntl.flock(lockf, fcntl.LOCK_EX)
            try:
                # Re-check under the lock: another process may have built it.
                if (not os.path.exists(_LIB)
                        or os.path.getmtime(_LIB) < os.path.getmtime(_SRC)):
                    tmp = f"{_LIB}.tmp.{os.getpid()}"
                    cmd = [
                        "g++", "-O2", "-g", "-std=c++17", "-shared", "-fPIC",
                        "-Wall", "-o", tmp, _SRC, "-lpthread",
                    ]
                    subprocess.run(cmd, check=True, capture_output=True,
                                   text=True)
                    os.replace(tmp, _LIB)
            finally:
                fcntl.flock(lockf, fcntl.LOCK_UN)
    return _LIB
