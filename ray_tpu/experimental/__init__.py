"""Experimental substrate for compiled DAGs (ref: python/ray/experimental/
channel.py — mutable-object channels backing accelerated DAGs)."""
from ray_tpu.experimental.channel import (  # noqa: F401
    Channel,
    ChannelClosedError,
    ChannelTimeoutError,
)
