"""Mutable shared-memory ring channels for compiled DAGs.

Analogue of the reference's experimental channels
(ref: python/ray/experimental/channel.py:50 `Channel`, backed by the C++
mutable-object manager, src/ray/core_worker/experimental_mutable_object_
manager.h:34): a shared-memory ring that one writer fills version-by-
version and N readers consume in order — the per-call task-submission
path (lease + RPC + object store) is bypassed entirely, which is the
whole point of compiled DAGs. The ring depth (`num_slots`) is the
per-edge pipelining budget: up to `num_slots` executions can be in
flight across a stage boundary before the writer blocks (the reference
gets the same effect from its buffered mutable objects).

Implementation: one mmap'd file in /dev/shm per channel:

    header:  magic u32 | closed u32 | slot_cap u64 | n_readers u64
             | n_slots u64 | w_seq u64
    acks:    n_readers x u64     (last version each reader consumed)
    slots:   n_slots x [ state u64 | len u64 | payload slot_cap ]

Version v (1-based) lives in slot (v-1) % n_slots. Slot state is a
seqlock: 2v-1 while the writer fills it, 2v once published.

  write(v): wait until v - min(acks) <= n_slots (ring has a free slot —
            built-in backpressure), fill the slot, publish.
  read():   wait for state == 2v of the next version's slot, copy out,
            re-check the state (a concurrent overwrite restarts), ack v.

Synchronization is polling with exponential backoff (bounds from
`RAY_TPU_CHANNEL_BACKOFF_US_MIN/MAX`, default 1µs..200µs): at
compiled-DAG rates the next version is almost always already there, so
the fast path is two mmap reads — no syscalls, no locks. Once the
backoff saturates the waiter also sched_yield()s so a busy peer pinned
to the same core can make progress.

Cross-host edges: readers always consume a LOCAL ring; a producer on a
different host writes through `RemoteChannelWriter`, which pushes the
serialized payload as a raw frame to the reader node's daemon
(`NodeDaemon.channel_push`) where it lands in the ring via the same
publish path. Ring backpressure propagates across the hop because the
push reply waits for the ring write. `FanoutWriter` fans one producer
out to consumer groups on several nodes (serialize once, publish per
node).
"""
from __future__ import annotations

import os
import mmap
import pickle
import struct
import time
import uuid
from typing import Any, List, Optional

from ray_tpu.core.config import get_config

try:
    import cloudpickle  # type: ignore
except ImportError:  # pragma: no cover
    from ray_tpu.core import serialization as _ser

    cloudpickle = _ser.cloudpickle

MAGIC = 0x52544348  # "RTCH"
_HDR = struct.Struct("<IIQQQQ")  # magic, closed, slot_cap, n_readers,
                                 # n_slots, w_seq
_U64 = struct.Struct("<Q")
_ACKS_OFF = _HDR.size
_WSEQ_OFF = 32      # header: magic(4) closed(4) cap@8 n_readers@16
                    #         n_slots@24 w_seq@32

DEFAULT_CAPACITY = 4 << 20
DEFAULT_SLOTS = 8


class ChannelClosedError(Exception):
    """The channel was torn down (compiled DAG teardown or actor death)."""


class ChannelTimeoutError(Exception):
    pass


class Channel:
    """One single-writer, n-reader shm ring.

    Create once (driver side) with `Channel.create(...)`; endpoints
    receive the pickled handle and lazily mmap the same file. Each reader
    must use a distinct `reader_idx` in [0, n_readers).
    """

    def __init__(self, path: str, capacity: int, n_readers: int,
                 n_slots: int = DEFAULT_SLOTS):
        self.path = path
        self.capacity = capacity        # payload bytes per slot
        self.n_readers = n_readers
        self.n_slots = n_slots
        self._mm: Optional[mmap.mmap] = None
        self._last_read: Optional[int] = None  # last consumed version
        self._w_seq: Optional[int] = None

    # -- layout ---------------------------------------------------------
    def _slots_off(self) -> int:
        return _ACKS_OFF + 8 * self.n_readers

    def _slot_off(self, idx: int) -> int:
        return self._slots_off() + idx * (16 + self.capacity)

    def _file_size(self) -> int:
        return self._slots_off() + self.n_slots * (16 + self.capacity)

    # -- lifecycle ------------------------------------------------------
    @classmethod
    def create(cls, n_readers: int, capacity: int = DEFAULT_CAPACITY,
               n_slots: int = DEFAULT_SLOTS,
               directory: str = "/dev/shm") -> "Channel":
        path = os.path.join(directory, f"rtpu_chan_{uuid.uuid4().hex}")
        ch = cls(path, capacity, n_readers, n_slots)
        with open(path, "wb") as f:
            f.write(_HDR.pack(MAGIC, 0, capacity, n_readers, n_slots, 0))
            f.truncate(ch._file_size())
            f.flush()
        return ch

    def _map(self) -> mmap.mmap:
        if self._mm is None:
            fd = os.open(self.path, os.O_RDWR)
            try:
                self._mm = mmap.mmap(fd, self._file_size())
            finally:
                os.close(fd)
            magic, _, cap, nr, ns, _ = _HDR.unpack_from(self._mm, 0)
            if magic != MAGIC or cap != self.capacity \
                    or ns != self.n_slots:
                raise ValueError(f"not a channel file: {self.path}")
        return self._mm

    def close(self) -> None:
        """Mark closed: every blocked/future read or write raises."""
        try:
            mm = self._map()
            struct.pack_into("<I", mm, 4, 1)
        except (OSError, ValueError):
            pass

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
        if self._mm is not None:
            try:
                self._mm.close()
            except Exception:  # noqa: BLE001
                pass
            self._mm = None

    def _closed(self, mm) -> bool:
        return struct.unpack_from("<I", mm, 4)[0] != 0

    # -- protocol -------------------------------------------------------
    def _wait(self, cond, mm, timeout: Optional[float], what: str):
        cfg = get_config()
        backoff = max(cfg.channel_backoff_us_min, 0.01) * 1e-6
        cap = max(cfg.channel_backoff_us_max * 1e-6, backoff)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            v = cond()
            if v is not None:
                return v
            if self._closed(mm):
                raise ChannelClosedError(self.path)
            if deadline is not None and time.monotonic() >= deadline:
                raise ChannelTimeoutError(f"{what} timed out on {self.path}")
            if backoff >= cap:
                # Saturated: stop trusting the timer alone — explicitly
                # cede the core so a same-core peer can publish/ack.
                os.sched_yield()
            time.sleep(backoff)
            backoff = min(backoff * 2, cap)

    def _min_ack(self, mm) -> int:
        return min(_U64.unpack_from(mm, _ACKS_OFF + 8 * i)[0]
                   for i in range(self.n_readers))

    def version(self) -> int:
        """Last published version (0 before the first write)."""
        return _U64.unpack_from(self._map(), _WSEQ_OFF)[0]

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        self.write_bytes(cloudpickle.dumps(value), timeout)

    def write_bytes(self, data, timeout: Optional[float] = 30.0) -> int:
        """Publish one already-serialized payload; returns the version it
        landed as. Split from `write` so the daemon's channel_push can
        land raw-frame payloads without a deserialize/re-serialize hop."""
        mm = self._map()
        if len(data) > self.capacity:
            raise ValueError(
                f"serialized value ({len(data)}B) exceeds channel slot "
                f"capacity ({self.capacity}B); recreate the DAG with a "
                f"larger buffer_size_bytes")
        if self._w_seq is None:  # attach: recover the write counter
            self._w_seq = _U64.unpack_from(mm, _WSEQ_OFF)[0]
        v = self._w_seq + 1

        def slot_free():
            # Ring has room once every reader is within n_slots of v.
            if v - self._min_ack(mm) <= self.n_slots:
                return True
            return None

        self._wait(slot_free, mm, timeout, "write (readers lagging)")
        off = self._slot_off((v - 1) % self.n_slots)
        _U64.pack_into(mm, off, 2 * v - 1)           # writing
        mm[off + 16:off + 16 + len(data)] = data
        _U64.pack_into(mm, off + 8, len(data))
        _U64.pack_into(mm, off, 2 * v)               # published
        _U64.pack_into(mm, _WSEQ_OFF, v)
        self._w_seq = v
        return v

    def _recover_last_read(self, mm, reader_idx: int) -> int:
        """First touch in this process: resume from the reader's ack word
        in shared memory (mirror of the writer's _w_seq recovery) — a
        restarted/re-unpickled reader that starts at 0 would wait forever
        for a version whose slot was overwritten long ago."""
        if self._last_read is None:
            self._last_read = _U64.unpack_from(
                mm, _ACKS_OFF + 8 * reader_idx)[0]
        return self._last_read

    def peek_ready(self, reader_idx: int = 0) -> bool:
        """Is the next version already published? (non-consuming)."""
        mm = self._map()
        v = self._recover_last_read(mm, reader_idx) + 1
        off = self._slot_off((v - 1) % self.n_slots)
        return _U64.unpack_from(mm, off)[0] == 2 * v

    def read(self, timeout: Optional[float] = None,
             reader_idx: int = 0) -> Any:
        mm = self._map()
        v = self._recover_last_read(mm, reader_idx) + 1
        off = self._slot_off((v - 1) % self.n_slots)

        def published():
            return True if _U64.unpack_from(mm, off)[0] == 2 * v else None

        while True:
            self._wait(published, mm, timeout, "read")
            n = _U64.unpack_from(mm, off + 8)[0]
            data = bytes(mm[off + 16:off + 16 + n])
            if _U64.unpack_from(mm, off)[0] == 2 * v:
                break  # seqlock validation: no concurrent overwrite
        self._last_read = v
        _U64.pack_into(mm, _ACKS_OFF + 8 * reader_idx, v)
        return pickle.loads(data)

    def __reduce__(self):
        return (Channel,
                (self.path, self.capacity, self.n_readers, self.n_slots))


class RemoteChannelWriter:
    """Writer endpoint for a ring that lives on ANOTHER node.

    The ring file is mmap'd only on the reader's node; this side pushes
    each serialized payload as a raw frame (wire codec 2) to that node's
    daemon, which lands it in the ring through the same `write_bytes`
    publish path. Backpressure crosses the hop because the push reply is
    not sent until the ring write completes (or times out).

    Writes are versioned and the daemon dedupes (`version <= w_seq` is
    an ack for an already-landed write), so a reply lost to a transport
    error can be retried without double-publishing.
    """

    def __init__(self, daemon_address: str, path: str, capacity: int,
                 n_readers: int, n_slots: int = DEFAULT_SLOTS):
        self.daemon_address = daemon_address
        self.path = path
        self.capacity = capacity
        self.n_readers = n_readers
        self.n_slots = n_slots
        self._client = None
        self._w_seq: Optional[int] = None

    def _rpc(self):
        if self._client is None:
            from ray_tpu.core.distributed.rpc import SyncRpcClient

            self._client = SyncRpcClient(self.daemon_address)
        return self._client

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        self.write_bytes(cloudpickle.dumps(value), timeout)

    def write_bytes(self, data, timeout: Optional[float] = 30.0) -> int:
        from ray_tpu.core.distributed import wire

        if len(data) > self.capacity:
            raise ValueError(
                f"serialized value ({len(data)}B) exceeds channel slot "
                f"capacity ({self.capacity}B); recreate the DAG with a "
                f"larger buffer_size_bytes")
        deadline = None if timeout is None else time.monotonic() + timeout
        if self._w_seq is None:  # attach: recover from the ring's w_seq
            rep = self._rpc().call(
                "NodeDaemon", "channel_version", path=self.path,
                timeout=30.0, idempotent=True)
            if rep.get("closed"):
                raise ChannelClosedError(self.path)
            self._w_seq = int(rep.get("version", 0))
        v = self._w_seq + 1
        attempts = 0
        while True:
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise ChannelTimeoutError(
                    f"remote write timed out on {self.path}")
            try:
                rep = self._rpc().call(
                    "NodeDaemon", "channel_push", path=self.path,
                    capacity=self.capacity, n_readers=self.n_readers,
                    n_slots=self.n_slots, version=v,
                    push_timeout=remaining, data=wire.Raw(data),
                    timeout=None if remaining is None else remaining + 10)
            except (ChannelClosedError, ChannelTimeoutError):
                raise
            except Exception as e:  # noqa: BLE001 — transport failure
                attempts += 1
                # Versioned dedupe makes the retry safe; but a dead
                # daemon means dead readers, so don't spin forever.
                if attempts >= 3 and deadline is None:
                    raise ChannelClosedError(
                        f"push to {self.daemon_address} failed: {e}")
                time.sleep(min(0.05 * attempts, 0.5))
                continue
            if rep.get("closed"):
                raise ChannelClosedError(self.path)
            if rep.get("timeout"):
                raise ChannelTimeoutError(
                    f"remote write (readers lagging) timed out on "
                    f"{self.path}")
            if rep.get("error"):
                raise RuntimeError(
                    f"channel_push {self.path}: {rep['error']}")
            self._w_seq = v
            return v

    def close(self) -> None:
        try:
            self._rpc().call("NodeDaemon", "channel_close",
                             path=self.path, timeout=10.0)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            pass

    def unlink(self) -> None:
        try:
            self._rpc().call("NodeDaemon", "channel_unlink",
                             path=self.path, timeout=10.0)
        except Exception:  # noqa: BLE001
            pass
        if self._client is not None:
            try:
                self._client.close()
            except Exception:  # noqa: BLE001
                pass
            self._client = None

    def __reduce__(self):
        return (RemoteChannelWriter,
                (self.daemon_address, self.path, self.capacity,
                 self.n_readers, self.n_slots))


class FanoutWriter:
    """One producer, consumer groups on several nodes: serialize once,
    publish into each group's ring (local `Channel` or
    `RemoteChannelWriter`). Aggregate backpressure is the slowest
    group's — version v+n_slots can't publish anywhere until every
    group acked v."""

    def __init__(self, endpoints: List[Any]):
        self.endpoints = list(endpoints)
        self._iter = 0                      # completed fan-out writes
        self._done = [0] * len(self.endpoints)

    def write(self, value: Any, timeout: Optional[float] = 30.0) -> None:
        self.write_bytes(cloudpickle.dumps(value), timeout)

    def write_bytes(self, data, timeout: Optional[float] = 30.0) -> None:
        # A timeout on a slow group leaves the fan-out PARTIAL; callers
        # retry the same payload, so remember which endpoints already
        # landed this iteration and skip them (a local ring has no
        # version dedupe — re-writing it would double-publish).
        target = self._iter + 1
        for i, ep in enumerate(self.endpoints):
            if self._done[i] >= target:
                continue
            ep.write_bytes(data, timeout)
            self._done[i] = target
        self._iter = target

    def close(self) -> None:
        for ep in self.endpoints:
            ep.close()

    def unlink(self) -> None:
        for ep in self.endpoints:
            ep.unlink()

    def __reduce__(self):
        return (FanoutWriter, (self.endpoints,))
