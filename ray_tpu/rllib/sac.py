"""SAC: soft actor-critic for continuous control.

ref: rllib/algorithms/sac/sac.py:1 (config surface: twin Q, target
entropy auto-tuning, polyak target updates; training_step: sample ->
replay -> K updates). TPU-first shape: critic, actor, AND temperature
update fuse into ONE jitted program per sampled batch — clipped
double-Q entropy-regularized TD targets, reparameterized actor loss
through min(Q1,Q2), alpha gradient against the target entropy, and the
polyak target move, all inside a single XLA computation (the reference
runs three torch optimizer steps with host round-trips in between).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.models import (
    apply_sac_actor,
    apply_twin_q,
    init_sac_actor,
    init_twin_q,
    sample_squashed,
)
from ray_tpu.rllib.replay_buffer import ReplayBuffer


@dataclasses.dataclass(frozen=True)
class SACHyperparams:
    actor_lr: float = 3e-4
    critic_lr: float = 3e-4
    alpha_lr: float = 3e-4
    gamma: float = 0.99
    tau: float = 0.005                 # polyak target rate
    target_entropy: float = -1.0       # default: -act_dim
    act_limit: float = 1.0
    init_alpha: float = 0.1


class SACLearner(Learner):
    """All three optimizers + the target move in one jitted update
    (ported onto the core Learner base, ref: learner.py:107; a mesh —
    usually from LearnerGroup — shards the batch over `dp`)."""

    _state_attrs = ("actor", "critic", "target_critic", "log_alpha",
                    "actor_opt", "critic_opt", "alpha_opt", "_rng")

    def __init__(self, obs_dim: int, act_dim: int, hp: SACHyperparams,
                 seed: int = 0, hidden=(64, 64),
                 mesh: Optional[Mesh] = None):
        self.hp = hp
        self.mesh = mesh
        rng = jax.random.PRNGKey(seed)
        r1, r2, self._rng = jax.random.split(rng, 3)
        self.actor = self._replicate(
            init_sac_actor(r1, obs_dim, act_dim, hidden))
        self.critic = self._replicate(
            init_twin_q(r2, obs_dim, act_dim, hidden))
        self.target_critic = jax.tree_util.tree_map(jnp.copy, self.critic)
        self.log_alpha = self._replicate(jnp.log(jnp.float32(hp.init_alpha)))
        self._actor_tx = optax.adam(hp.actor_lr)
        self._critic_tx = optax.adam(hp.critic_lr)
        self._alpha_tx = optax.adam(hp.alpha_lr)
        self.actor_opt = self._replicate(self._actor_tx.init(self.actor))
        self.critic_opt = self._replicate(self._critic_tx.init(self.critic))
        self.alpha_opt = self._replicate(self._alpha_tx.init(self.log_alpha))
        self._update = self._build_update()

    def _build_update(self):
        hp = self.hp

        def critic_loss_fn(critic, actor, target_critic, log_alpha,
                           batch, key):
            mu, log_std = apply_sac_actor(actor, batch["next_obs"])
            next_a, next_logp = sample_squashed(mu, log_std, key,
                                                hp.act_limit)
            tq1, tq2 = apply_twin_q(target_critic, batch["next_obs"],
                                    next_a)
            alpha = jnp.exp(log_alpha)
            next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = jax.lax.stop_gradient(
                batch["rewards"]
                + hp.gamma * (1.0 - batch["terminals"]) * next_v)
            q1, q2 = apply_twin_q(critic, batch["obs"], batch["actions"])
            return ((q1 - target) ** 2 + (q2 - target) ** 2).mean()

        def actor_loss_fn(actor, critic, log_alpha, batch, key):
            mu, log_std = apply_sac_actor(actor, batch["obs"])
            a, logp = sample_squashed(mu, log_std, key, hp.act_limit)
            q1, q2 = apply_twin_q(critic, batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            loss = (alpha * logp - jnp.minimum(q1, q2)).mean()
            return loss, logp

        def alpha_loss_fn(log_alpha, logp):
            # Gradient pushes alpha so E[-logp] tracks target entropy.
            return -(log_alpha * jax.lax.stop_gradient(
                logp + hp.target_entropy)).mean()

        def update(actor, critic, target_critic, log_alpha,
                   actor_opt, critic_opt, alpha_opt, batch, key):
            k1, k2 = jax.random.split(key)
            c_loss, c_grads = jax.value_and_grad(critic_loss_fn)(
                critic, actor, target_critic, log_alpha, batch, k1)
            c_up, critic_opt = self._critic_tx.update(c_grads, critic_opt,
                                                      critic)
            critic = optax.apply_updates(critic, c_up)

            (a_loss, logp), a_grads = jax.value_and_grad(
                actor_loss_fn, has_aux=True)(actor, critic, log_alpha,
                                             batch, k2)
            a_up, actor_opt = self._actor_tx.update(a_grads, actor_opt,
                                                    actor)
            actor = optax.apply_updates(actor, a_up)

            al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(
                log_alpha, logp)
            al_up, alpha_opt = self._alpha_tx.update(al_grad, alpha_opt,
                                                     log_alpha)
            log_alpha = optax.apply_updates(log_alpha, al_up)

            target_critic = jax.tree_util.tree_map(
                lambda t, s: (1.0 - hp.tau) * t + hp.tau * s,
                target_critic, critic)
            metrics = {"critic_loss": c_loss, "actor_loss": a_loss,
                       "alpha": jnp.exp(log_alpha),
                       "entropy": -logp.mean()}
            return (actor, critic, target_critic, log_alpha,
                    actor_opt, critic_opt, alpha_opt, metrics)

        return self._jit_update(
            update, num_state_args=7,
            batch_keys=("obs", "actions", "rewards", "next_obs",
                        "terminals"))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self._rng, key = jax.random.split(self._rng)
        jbatch = self._shard_batch(
            {k: jnp.asarray(v) for k, v in batch.items()
             if k != "batch_indexes"})
        (self.actor, self.critic, self.target_critic, self.log_alpha,
         self.actor_opt, self.critic_opt, self.alpha_opt,
         metrics) = self._update(
            self.actor, self.critic, self.target_critic, self.log_alpha,
            self.actor_opt, self.critic_opt, self.alpha_opt, jbatch, key)
        return {k: float(v) for k, v in metrics.items()}

    # Rollout/eval workers only need the ACTOR pytree.
    def get_weights(self) -> Any:
        return jax.device_get(self.actor)

    def set_weights(self, actor: Any) -> None:
        self.actor = self._replicate(actor)


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=SAC)
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005
        self.train_batch_size = 256
        self.num_updates_per_iteration = 64
        self.replay_buffer_capacity = 100_000
        self.learning_starts = 1000       # uniform-random warmup steps
        self.target_entropy = None        # None -> -act_dim

    def training(self, *, actor_lr=None, critic_lr=None, alpha_lr=None,
                 gamma=None, tau=None, train_batch_size=None,
                 num_updates_per_iteration=None,
                 replay_buffer_capacity=None, learning_starts=None,
                 target_entropy=None, **kwargs) -> "SACConfig":
        for k, v in dict(
                actor_lr=actor_lr, critic_lr=critic_lr, alpha_lr=alpha_lr,
                gamma=gamma, tau=tau, train_batch_size=train_batch_size,
                num_updates_per_iteration=num_updates_per_iteration,
                replay_buffer_capacity=replay_buffer_capacity,
                learning_starts=learning_starts,
                target_entropy=target_entropy).items():
            if v is not None:
                setattr(self, k, v)
        return super().training(**kwargs)


class SAC(Algorithm):
    """training_step: stochastic-actor collection into replay (uniform
    random during warmup), K fused updates per iteration."""

    _eval_mode = "sac_mean"

    def _setup_learner(self, obs_dim: int, num_actions: int) -> SACLearner:
        cfg: SACConfig = self.config
        info = self.space_info
        if not info["continuous"]:
            raise ValueError("SAC needs a continuous-control env "
                             "(e.g. Pendulum-v1)")
        act_dim = info["act_dim"]
        hp = SACHyperparams(
            actor_lr=cfg.actor_lr, critic_lr=cfg.critic_lr,
            alpha_lr=cfg.alpha_lr, gamma=cfg.gamma, tau=cfg.tau,
            target_entropy=(cfg.target_entropy
                            if cfg.target_entropy is not None
                            else -float(act_dim)),
            act_limit=info["act_limit"])
        self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                   seed=cfg.seed)
        self._env_steps = 0
        seed, hidden = cfg.seed, cfg.model_hidden

        def factory(mesh=None):
            return SACLearner(obs_dim, act_dim, hp, seed=seed,
                              hidden=hidden, mesh=mesh)

        return self._build_learner(factory)

    def _collect(self, uniform: bool):
        T = self.config.rollout_fragment_length
        if self._remote:
            import ray_tpu

            outs = ray_tpu.get(
                [w.sample_transitions_continuous.remote(T, uniform=uniform)
                 for w in self.workers], timeout=600)
        else:
            outs = [self.workers[0].sample_transitions_continuous(
                T, uniform=uniform)]
        batch = {k: np.concatenate([o["batch"][k] for o in outs])
                 for k in outs[0]["batch"]}
        returns = [r for o in outs for r in o["episode_returns"]]
        return self._apply_learner_connector(batch), returns

    def training_step(self) -> Dict[str, float]:
        cfg: SACConfig = self.config
        warmup = self._env_steps < cfg.learning_starts
        batch, episode_returns = self._collect(uniform=warmup)
        self.replay.add_batch(batch)
        self._env_steps += len(batch["rewards"])

        metrics: Dict[str, float] = {}
        if not warmup and len(self.replay) >= cfg.train_batch_size:
            agg: Dict[str, list] = {}
            for _ in range(cfg.num_updates_per_iteration):
                sample = self.replay.sample(cfg.train_batch_size)
                m = self.learner.update(sample)
                for k, v in m.items():
                    agg.setdefault(k, []).append(v)
            metrics.update({k: float(np.mean(v)) for k, v in agg.items()})
            self._broadcast_weights()
        if episode_returns:
            metrics["episode_return_mean"] = float(np.mean(episode_returns))
        metrics["num_env_steps_sampled"] = float(self._env_steps)
        return metrics
