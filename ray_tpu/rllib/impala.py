"""IMPALA: asynchronous actor-learner RL with V-trace correction.

ref: rllib/algorithms/impala/impala.py (decoupled sampling/learning with
a sample queue) and the V-trace returns of Espeholt et al. 2018. TPU-
first shape: the learner is ONE jitted program — target-policy logp,
clipped importance ratios, the V-trace reverse scan, and the combined
policy/value/entropy losses all fuse under `jax.jit` (`lax.scan` for the
temporal recursion, static shapes throughout). Asynchrony comes from the
runtime: rollout workers sample with whatever weights they last
received, a queue of in-flight sample refs keeps the learner fed, and
staleness is exactly what V-trace corrects.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.models import apply_mlp_policy, init_mlp_policy


@dataclasses.dataclass(frozen=True)
class ImpalaHyperparams:
    lr: float = 6e-4
    gamma: float = 0.99
    rho_clip: float = 1.0
    c_clip: float = 1.0
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    grad_clip: float = 40.0


class ImpalaLearner(Learner):
    """Ported onto the core Learner base: state plumbing inherited;
    a mesh (from LearnerGroup) shards the [E, T] batch over `dp`."""

    _state_attrs = ("params", "opt_state")

    def __init__(self, obs_dim: int, num_actions: int,
                 hp: ImpalaHyperparams, seed: int = 0, hidden=(64, 64),
                 mesh=None):
        self.hp = hp
        self.mesh = mesh
        rng = jax.random.PRNGKey(seed)
        self.params = self._replicate(
            init_mlp_policy(rng, obs_dim, num_actions, hidden))
        self._tx = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip),
            optax.rmsprop(hp.lr, decay=0.99, eps=0.1),
        )
        self.opt_state = self._replicate(self._tx.init(self.params))
        self._update = self._build_update()

    def _build_update(self):
        hp = self.hp

        def vtrace(behavior_logp, target_logp, rewards, dones, values,
                   final_value):
            """V-trace targets + pg advantages; all inputs [E, T]."""
            rho = jnp.minimum(jnp.exp(target_logp - behavior_logp),
                              hp.rho_clip)
            c = jnp.minimum(jnp.exp(target_logp - behavior_logp),
                            hp.c_clip)
            v_next = jnp.concatenate(
                [values[:, 1:], final_value[:, None]], axis=1)
            not_done = 1.0 - dones
            deltas = rho * (rewards + hp.gamma * not_done * v_next
                            - values)

            def step(acc, xs):
                delta, nd, c_t = xs
                acc = delta + hp.gamma * nd * c_t * acc
                return acc, acc

            _, acc = jax.lax.scan(
                step, jnp.zeros(values.shape[0]),
                (deltas.T, not_done.T, c.T), reverse=True)
            vs = values + acc.T
            vs_next = jnp.concatenate(
                [vs[:, 1:], final_value[:, None]], axis=1)
            pg_adv = rho * (rewards + hp.gamma * not_done * vs_next
                            - values)
            return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)

        def loss_fn(params, batch):
            E, T = batch["rewards"].shape
            obs = batch["obs"].reshape(E * T, -1)
            logits, value = apply_mlp_policy(params, obs)
            logits = logits.reshape(E, T, -1)
            value = value.reshape(E, T)
            logp_all = jax.nn.log_softmax(logits)
            target_logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=2)[..., 0]
            vs, pg_adv = vtrace(batch["logp"], target_logp,
                                batch["rewards"], batch["dones"], value,
                                batch["final_value"])
            pg_loss = self._pg_loss(target_logp, batch["logp"], pg_adv)
            vf_loss = 0.5 * jnp.mean(jnp.square(value - vs))
            entropy = -jnp.mean(
                jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            loss = (pg_loss + hp.vf_loss_coeff * vf_loss
                    - hp.entropy_coeff * entropy)
            return loss, {"policy_loss": pg_loss, "vf_loss": vf_loss,
                          "entropy": entropy,
                          "mean_rho": jnp.mean(
                              jnp.exp(target_logp - batch["logp"]))}

        def update(params, opt_state, batch):
            (_, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, metrics

        return self._jit_update(
            update, num_state_args=2, has_rng=False,
            batch_keys=("obs", "actions", "logp", "rewards", "dones",
                        "final_value"))

    def _pg_loss(self, target_logp, behavior_logp, pg_adv):
        """Policy-gradient term; APPO overrides with the clipped
        surrogate (traced inside _build_update's jit)."""
        return -jnp.mean(target_logp * pg_adv)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        jbatch = self._shard_batch(
            {k: jnp.asarray(v) for k, v in batch.items()
             if k != "values"})
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, jbatch)
        return {k: float(v) for k, v in metrics.items()}


class ImpalaConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=IMPALA)
        self.lr = 6e-4
        self.gamma = 0.99
        self.rho_clip = 1.0
        self.c_clip = 1.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.grad_clip = 40.0
        self.queue_depth = 2          # in-flight sample batches per worker
        self.broadcast_interval = 1   # learner updates between weight syncs

    def training(self, *, lr=None, gamma=None, rho_clip=None, c_clip=None,
                 vf_loss_coeff=None, entropy_coeff=None, grad_clip=None,
                 queue_depth=None, broadcast_interval=None,
                 **kwargs) -> "ImpalaConfig":
        for k, v in dict(lr=lr, gamma=gamma, rho_clip=rho_clip,
                         c_clip=c_clip, vf_loss_coeff=vf_loss_coeff,
                         entropy_coeff=entropy_coeff, grad_clip=grad_clip,
                         queue_depth=queue_depth,
                         broadcast_interval=broadcast_interval).items():
            if v is not None:
                setattr(self, k, v)
        return super().training(**kwargs)

    def hyperparams(self) -> ImpalaHyperparams:
        return ImpalaHyperparams(
            lr=self.lr, gamma=self.gamma, rho_clip=self.rho_clip,
            c_clip=self.c_clip, vf_loss_coeff=self.vf_loss_coeff,
            entropy_coeff=self.entropy_coeff, grad_clip=self.grad_clip)


class IMPALA(Algorithm):
    """training_step: consume the oldest ready sample batch (collected
    under stale weights — V-trace corrects), update, refill the in-flight
    queue, broadcast weights on the configured cadence."""

    _learner_cls = ImpalaLearner   # APPO swaps in AppoLearner

    def _setup_learner(self, obs_dim: int, num_actions: int
                       ) -> ImpalaLearner:
        cfg: ImpalaConfig = self.config
        self._pending: List[Any] = []
        self._updates_since_broadcast = 0
        self._next_worker = 0
        cls, hp = self._learner_cls, cfg.hyperparams()
        seed, hidden = cfg.seed, cfg.model_hidden

        def factory(mesh=None):
            return cls(obs_dim, num_actions, hp, seed=seed,
                       hidden=hidden, mesh=mesh)

        return self._build_learner(factory)

    def _refill(self) -> None:
        cfg: ImpalaConfig = self.config
        T = cfg.rollout_fragment_length
        if self._remote:
            target = cfg.queue_depth * len(self.workers)
            while len(self._pending) < target:
                # Persistent round-robin: resetting per call would pile
                # all steady-state refills onto worker 0 and starve the
                # rest.
                w = self.workers[self._next_worker % len(self.workers)]
                self._next_worker += 1
                self._pending.append(w.sample.remote(T))
        else:
            while len(self._pending) < 1:
                self._pending.append(self.workers[0].sample(T))

    def training_step(self) -> Dict[str, float]:
        import ray_tpu

        self._refill()
        if self._remote:
            done, rest = ray_tpu.wait(self._pending, num_returns=1,
                                      timeout=600)
            if not done:
                raise TimeoutError(
                    "no rollout worker produced a sample batch within "
                    "600s — check worker health (`ray-tpu list workers`)")
            self._pending = rest
            out = ray_tpu.get(done[0])
        else:
            out = self._pending.pop(0)
        batch = out["batch"]
        metrics = self.learner.update(batch)
        self._updates_since_broadcast += 1
        cfg: ImpalaConfig = self.config
        if self._updates_since_broadcast >= cfg.broadcast_interval:
            self._broadcast_weights()
            self._updates_since_broadcast = 0
        self._refill()   # keep samplers busy while we return
        if out["episode_returns"]:
            metrics["episode_return_mean"] = float(
                np.mean(out["episode_returns"]))
            metrics["num_episodes"] = float(len(out["episode_returns"]))
        metrics["num_env_steps_sampled"] = float(batch["rewards"].size)
        return metrics
