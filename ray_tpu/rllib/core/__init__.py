"""RLlib new-stack core: RLModule / Learner / LearnerGroup
(ref: rllib/core/rl_module/rl_module.py, rllib/core/learner/learner.py:107,
rllib/core/learner/learner_group.py:60)."""
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import (
    DiscreteQModule,
    MLPPolicyModule,
    MultiRLModule,
    RLModule,
)

__all__ = [
    "DiscreteQModule",
    "Learner",
    "LearnerGroup",
    "MLPPolicyModule",
    "MultiRLModule",
    "RLModule",
]
