"""Learner: owns params + optimizer state + ONE jitted fused update.

ref: rllib/core/learner/learner.py:107 — the reference Learner holds an
RLModule and optimizers and runs `update_from_batch`; gradient transport
between learners is torch-DDP.

TPU-first divergence: a Learner subclass compiles its ENTIRE training
iteration (loss, every SGD epoch/minibatch, optimizer moves, target
nets) into one jitted SPMD program. Data parallelism is then a mesh
sharding annotation on the batch arguments — XLA inserts the gradient
psums inside the program — rather than a gradient-hook wrapper class
(see LearnerGroup). Multi-host scale runs the SAME program under
`jax.distributed` instead of wiring NCCL process groups.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class Learner:
    """Base: shared state plumbing; subclasses build the fused update.

    Contract: set `_state_attrs` to the attribute names making up the
    full training state (leading underscores are stripped in the
    serialized keys), keep the mesh (or None) in `self.mesh`, implement
    `update(batch) -> metrics` calling the jitted program.
    """

    _state_attrs: Tuple[str, ...] = ()
    mesh: Optional[Mesh] = None

    # -- update ---------------------------------------------------------
    def update(self, batch: Dict[str, Any]) -> Dict[str, float]:
        raise NotImplementedError

    # -- jit wiring -----------------------------------------------------
    def _jit_update(self, update_fn, num_state_args: int,
                    batch_keys: Tuple[str, ...],
                    has_rng: bool = True,
                    out_spec: Optional[Tuple[str, ...]] = None,
                    donate: Optional[Tuple[int, ...]] = None):
        """Compile the fused update with donated state and, under a
        mesh, replicated-state / dp-sharded-batch shardings. Argument
        convention: `num_state_args` state pytrees, then the batch
        dict, then (when has_rng) an rng key. Outputs default to the
        new state pytrees plus a metrics dict (all replicated);
        `out_spec` overrides with per-output "rep"/"dp" markers (e.g.
        DQN returns per-sample TD errors, which stay dp-sharded).
        `donate` overrides which positional args are donated (default:
        every state arg; DQN keeps its target params undonated)."""
        if donate is None:
            donate = tuple(range(num_state_args))
        if self.mesh is None:
            return jax.jit(update_fn, donate_argnums=donate)
        rep = NamedSharding(self.mesh, P())
        dp = NamedSharding(self.mesh, P("dp"))
        batch_sh = {k: dp for k in batch_keys}
        tail = (batch_sh, rep) if has_rng else (batch_sh,)
        if out_spec is None:
            outs = (rep,) * (num_state_args + 1)
        else:
            outs = tuple(rep if s == "rep" else dp for s in out_spec)
        return jax.jit(
            update_fn, donate_argnums=donate,
            in_shardings=(rep,) * num_state_args + tail,
            out_shardings=outs)

    # -- device placement ----------------------------------------------
    def _replicate(self, tree: Any) -> Any:
        """Put a pytree on-device, replicated over the mesh if any."""
        if self.mesh is not None:
            return jax.device_put(tree, NamedSharding(self.mesh, P()))
        return jax.device_put(tree)

    def _shard_batch(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        """Shard batch leaves along axis 0 over the mesh `dp` axis."""
        if self.mesh is None:
            return batch
        dp = NamedSharding(self.mesh, P("dp"))
        return {k: jax.device_put(v, dp) for k, v in batch.items()}

    # -- weights (what rollout/eval workers need) -----------------------
    def get_weights(self) -> Any:
        return jax.device_get(self.params)

    def set_weights(self, params: Any) -> None:
        self.params = self._replicate(params)

    # -- full training state (exact resume; ref: Learner.get_state) -----
    def get_state(self) -> Dict[str, Any]:
        return {attr.lstrip("_"): jax.device_get(getattr(self, attr))
                for attr in self._state_attrs}

    def set_state(self, state: Dict[str, Any]) -> None:
        for attr in self._state_attrs:
            key = attr.lstrip("_")
            if key in state:
                setattr(self, attr, self._replicate(state[key]))
