"""RLModule: the network abstraction of the RLlib new stack.

ref: rllib/core/rl_module/rl_module.py — a module owns the neural nets
and exposes forward_train / forward_inference / forward_exploration;
learners own optimization, modules own computation.

TPU-first divergence: a module here holds NO parameters. `init(rng)`
returns a pytree and every forward is a pure function of (params, ...),
so the same module object can be closed over inside a jitted, donated,
mesh-sharded update program without host state sneaking into the trace
(the reference's torch modules carry their weights; ours are functional
like everything else in ray_tpu/models).
"""
from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from ray_tpu.rllib.models import (
    apply_mlp_policy,
    apply_mlp_q,
    init_mlp_policy,
    init_mlp_q,
)

Params = Any  # pytree


class RLModule:
    """Pure-function network bundle (ref: rl_module.py RLModule API)."""

    def init(self, rng: jax.Array) -> Params:
        raise NotImplementedError

    def forward_train(self, params: Params, obs: jnp.ndarray):
        """Everything the loss needs (e.g. logits AND value)."""
        raise NotImplementedError

    def forward_inference(self, params: Params, obs: jnp.ndarray):
        """Greedy/deterministic head for serving and evaluation."""
        raise NotImplementedError

    def forward_exploration(self, params: Params, obs: jnp.ndarray,
                            rng: jax.Array):
        """Stochastic head for rollout collection; defaults to
        inference (deterministic modules)."""
        return self.forward_inference(params, obs)


class MLPPolicyModule(RLModule):
    """Separate pi/v towers for actor-critic algorithms (PPO/IMPALA)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Params:
        return init_mlp_policy(rng, self.obs_dim, self.num_actions,
                               self.hidden)

    def forward_train(self, params: Params, obs: jnp.ndarray
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        return apply_mlp_policy(params, obs)  # (logits [B,A], value [B])

    def forward_inference(self, params: Params, obs: jnp.ndarray
                          ) -> jnp.ndarray:
        logits, _ = apply_mlp_policy(params, obs)
        return jnp.argmax(logits, axis=-1)

    def forward_exploration(self, params: Params, obs: jnp.ndarray,
                            rng: jax.Array) -> jnp.ndarray:
        logits, _ = apply_mlp_policy(params, obs)
        return jax.random.categorical(rng, logits, axis=-1)


class DiscreteQModule(RLModule):
    """Q(s, .) MLP for value-based algorithms (DQN family)."""

    def __init__(self, obs_dim: int, num_actions: int,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, rng: jax.Array) -> Params:
        return init_mlp_q(rng, self.obs_dim, self.num_actions, self.hidden)

    def forward_train(self, params: Params, obs: jnp.ndarray) -> jnp.ndarray:
        return apply_mlp_q(params, obs)  # Q [B, A]

    def forward_inference(self, params: Params, obs: jnp.ndarray
                          ) -> jnp.ndarray:
        return jnp.argmax(apply_mlp_q(params, obs), axis=-1)

    def forward_exploration(self, params: Params, obs: jnp.ndarray,
                            rng: jax.Array, epsilon: float = 0.05
                            ) -> jnp.ndarray:
        q = apply_mlp_q(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        k1, k2 = jax.random.split(rng)
        rand = jax.random.randint(k1, greedy.shape, 0, self.num_actions)
        explore = jax.random.uniform(k2, greedy.shape) < epsilon
        return jnp.where(explore, rand, greedy)


class MultiRLModule(RLModule):
    """Container of named sub-modules — the multi-agent / multi-policy
    module (ref: rl_module.py MultiRLModule). `init` returns a dict of
    per-module pytrees; forwards take the module id."""

    def __init__(self, modules: Dict[str, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, module_id: str) -> RLModule:
        return self._modules[module_id]

    def module_ids(self):
        return sorted(self._modules)

    def init(self, rng: jax.Array) -> Params:
        keys = jax.random.split(rng, len(self._modules))
        return {mid: self._modules[mid].init(k)
                for mid, k in zip(sorted(self._modules), keys)}

    def forward_train(self, params: Params, obs, module_id: str = None):
        if module_id is not None:
            return self._modules[module_id].forward_train(
                params[module_id], obs)
        return {mid: m.forward_train(params[mid], obs[mid])
                for mid, m in self._modules.items()}

    def forward_inference(self, params: Params, obs, module_id: str = None):
        if module_id is not None:
            return self._modules[module_id].forward_inference(
                params[module_id], obs)
        return {mid: m.forward_inference(params[mid], obs[mid])
                for mid, m in self._modules.items()}

    def forward_exploration(self, params: Params, obs, rng: jax.Array,
                            module_id: str = None):
        """Dispatch to submodules with a per-module rng fork (the base
        default would silently drop the rng and explore greedily)."""
        if module_id is not None:
            return self._modules[module_id].forward_exploration(
                params[module_id], obs, rng)
        keys = jax.random.split(rng, len(self._modules))
        return {mid: self._modules[mid].forward_exploration(
                    params[mid], obs[mid], k)
                for mid, k in zip(sorted(self._modules), keys)}
