"""LearnerGroup: data-parallel training across N learners.

ref: rllib/core/learner/learner_group.py:60 — the reference manages N
learner *actors*, shards each batch across them, and relies on torch
DDP for gradient sync.

TPU-first design — two modes:

**In-process SPMD (default).** `num_learners=N` claims N local devices
as a `dp` mesh axis and runs the learner's ONE fused pjit program over
it. The batch is sharded on axis 0, params are replicated, and XLA
inserts the gradient psums *inside* the program — per minibatch, per
epoch, wherever the math needs them. This is bit-identical to a single
learner on the concatenated batch (the psum of shard-means IS the
global mean), with zero host round-trips per sync. "DDP" is a sharding
annotation here, not a wrapper class; multi-host scale runs the same
program under `jax.distributed` over a host-spanning mesh.

**Remote actors (`remote=True`).** N `ray_tpu` actors each own a full
learner; per update the batch splits on axis 0, every actor runs the
fused update on its shard, then float state (params + optimizer
moments) tree-averages across actors — weighted by shard rows, so the
weighted mean of per-shard means IS the global mean — and is pushed
back: local-update parameter synchronization in two host RPC rounds
per update (update+collect, then broadcast) rather than one per
gradient. The weighted average of per-shard Adam updates is not
bitwise the global-batch update (same class of approximation as the
reference's per-minibatch advantage normalization), but actors stay
exactly synchronized after every update. Use this mode when learners
must live on different hosts without a shared jax runtime.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


def _tree_avg(trees: List[Any], weights: List[float]) -> Any:
    """Row-weighted elementwise mean over float leaves; first tree wins
    elsewhere (optimizer step counters must stay integral)."""
    import jax
    import jax.numpy as jnp

    total = float(sum(weights))
    frac = [w / total for w in weights]

    def avg(*leaves):
        if jnp.issubdtype(np.asarray(leaves[0]).dtype, jnp.floating):
            return sum(f * np.asarray(x, dtype=np.float64)
                       for f, x in zip(frac, leaves))
        return leaves[0]

    return jax.tree_util.tree_map(avg, *trees)


class _LearnerActor:
    """Runs one learner in a worker process (wrapped by ray_tpu.remote)."""

    def __init__(self, factory: Callable, index: int):
        self.index = index
        self.learner = factory(None)
        self._decorrelate_rng()

    def _decorrelate_rng(self) -> None:
        """Fork per-actor stochasticity (e.g. SAC action noise) while
        param init stays identical (the factory seed fixes init; only
        the running rng forks). Actor 0 keeps the canonical stream."""
        import jax

        if self.index and hasattr(self.learner, "_rng"):
            self.learner._rng = jax.random.fold_in(
                self.learner._rng, self.index)

    def update_and_collect(self, shard: Dict[str, np.ndarray]):
        """One fused update + the post-update sync state (folds the
        collect RPC into the update round)."""
        metrics = self.learner.update(shard)
        state = self.learner.get_state()
        state.pop("rng", None)  # each actor keeps its own stream
        return metrics, state

    def set_sync_state(self, state: Dict[str, Any]) -> None:
        self.learner.set_state(state)

    def get_weights(self) -> Any:
        return self.learner.get_weights()

    def set_weights(self, w: Any) -> None:
        self.learner.set_weights(w)

    def get_state(self) -> Dict[str, Any]:
        return self.learner.get_state()

    def set_state(self, state: Dict[str, Any]) -> None:
        self.learner.set_state(state)
        # A broadcast restore ships ONE rng to every actor; re-fork so
        # actors don't degenerate into N identically-noised copies.
        self._decorrelate_rng()


class LearnerGroup:
    """Drop-in for a single learner: update/get/set weights+state."""

    def __init__(self, factory: Callable, num_learners: int = 1,
                 remote: bool = False,
                 resources_per_learner: Optional[dict] = None):
        self._remote = remote and num_learners > 0
        self.num_learners = max(1, num_learners)
        if not self._remote:
            import jax
            from jax.sharding import Mesh

            devs = jax.devices()
            if len(devs) < self.num_learners:
                raise ValueError(
                    f"num_learners={self.num_learners} > "
                    f"{len(devs)} visible devices; use remote=True for "
                    f"learners beyond one host's devices")
            mesh = Mesh(np.array(devs[:self.num_learners]), ("dp",))
            self._learner = factory(mesh)
            if self._learner.mesh is not mesh:
                raise ValueError(
                    "learner factory ignored the group mesh; pass "
                    "mesh through to the Learner so the fused program "
                    "shards over dp")
        else:
            import ray_tpu

            opts = dict(resources_per_learner or {"num_cpus": 1})
            cls = ray_tpu.remote(**opts)(_LearnerActor)
            self._actors = [cls.remote(factory, i)
                            for i in range(self.num_learners)]
            # Surface constructor failures now, not at first update.
            ray_tpu.get([a.get_weights.remote() for a in self._actors],
                        timeout=300)

    # -- update ---------------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        if not self._remote:
            return self._learner.update(batch)
        import ray_tpu

        shards = self._split(batch)
        rows = [len(next(iter(s.values()))) for s in shards]
        # Round 1: update + collect state; round 2: broadcast average.
        outs = ray_tpu.get(
            [a.update_and_collect.remote(s)
             for a, s in zip(self._actors, shards)], timeout=600)
        metrics = [m for m, _ in outs]
        avg = _tree_avg([s for _, s in outs], rows)
        ref = ray_tpu.put(avg)
        ray_tpu.get([a.set_sync_state.remote(ref) for a in self._actors],
                    timeout=600)
        total = float(sum(rows))
        return {k: float(sum(r * m[k] for r, m in zip(rows, metrics))
                         / total)
                for k in metrics[0]}

    def _split(self, batch: Dict[str, np.ndarray]) -> List[Dict]:
        n = self.num_learners
        shards: List[Dict] = [{} for _ in range(n)]
        for k, v in batch.items():
            v = np.asarray(v)
            if v.ndim == 0 or len(v) < n:
                raise ValueError(
                    f"batch[{k!r}] has leading dim {v.shape} — cannot "
                    f"shard across {n} learners")
            for i, piece in enumerate(np.array_split(v, n)):
                shards[i][k] = piece
        return shards

    # -- weights / state ------------------------------------------------
    def get_weights(self) -> Any:
        if not self._remote:
            return self._learner.get_weights()
        import ray_tpu

        return ray_tpu.get(self._actors[0].get_weights.remote(),
                           timeout=300)

    def set_weights(self, w: Any) -> None:
        if not self._remote:
            self._learner.set_weights(w)
            return
        import ray_tpu

        ref = ray_tpu.put(w)
        ray_tpu.get([a.set_weights.remote(ref) for a in self._actors],
                    timeout=300)

    def get_state(self) -> Dict[str, Any]:
        if not self._remote:
            return self._learner.get_state()
        import ray_tpu

        return ray_tpu.get(self._actors[0].get_state.remote(),
                           timeout=300)

    def set_state(self, state: Dict[str, Any]) -> None:
        if not self._remote:
            self._learner.set_state(state)
            return
        import ray_tpu

        ref = ray_tpu.put(state)
        ray_tpu.get([a.set_state.remote(ref) for a in self._actors],
                    timeout=300)

    def shutdown(self) -> None:
        if self._remote:
            import ray_tpu

            for a in self._actors:
                try:
                    ray_tpu.kill(a)
                except Exception:  # noqa: BLE001
                    pass
            self._actors = []
