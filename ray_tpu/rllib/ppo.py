"""PPO: clipped-surrogate policy optimization, learner as ONE jitted
SPMD program.

Reference: rllib/algorithms/ppo/ppo.py (training_step), core/learner/
learner.py:107. TPU-first divergence: instead of a Python loop dispatching
per-minibatch torch steps, GAE + advantage normalization + every SGD
epoch/minibatch run inside a single `jax.jit` via nested `lax.scan` —
one dispatch per training iteration, static shapes throughout, shardable
over a mesh `dp` axis (params replicated, batch sharded).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import MLPPolicyModule, RLModule


@dataclasses.dataclass(frozen=True)
class PPOHyperparams:
    lr: float = 3e-4
    gamma: float = 0.99
    lambda_: float = 0.95
    clip_param: float = 0.2
    vf_loss_coeff: float = 0.5
    entropy_coeff: float = 0.01
    num_epochs: int = 4
    minibatch_size: int = 256
    grad_clip: float = 0.5


class PPOLearner(Learner):
    """Params + optimizer + the ONE jitted update (ref: Learner,
    core/learner/learner.py:107). Ported onto the core Learner base:
    state plumbing is inherited; a mesh (usually handed in by
    LearnerGroup) shards the batch over `dp` for in-program DDP."""

    _state_attrs = ("params", "opt_state", "_rng")

    def __init__(self, obs_dim: int, num_actions: int, hp: PPOHyperparams,
                 seed: int = 0, mesh: Optional[Mesh] = None,
                 hidden=(64, 64), module: Optional[RLModule] = None):
        self.hp = hp
        self.mesh = mesh
        self.module = module or MLPPolicyModule(obs_dim, num_actions,
                                                hidden)
        self._rng = jax.random.PRNGKey(seed)
        self._rng, init_key = jax.random.split(self._rng)
        self.params = self._replicate(self.module.init(init_key))
        self._tx = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip),
            optax.adam(hp.lr),
        )
        self.opt_state = self._replicate(self._tx.init(self.params))
        self._update = self._build_update()

    # -- the jitted program -------------------------------------------------
    def _build_update(self):
        hp = self.hp

        def gae(rewards, dones, values, final_value):
            """Reverse scan over time; [E, T] inputs."""
            def step(carry, xs):
                r, d, v, v_next = xs
                delta = r + hp.gamma * v_next * (1.0 - d) - v
                adv = delta + hp.gamma * hp.lambda_ * (1.0 - d) * carry
                return adv, adv

            v_next = jnp.concatenate(
                [values[:, 1:], final_value[:, None]], axis=1)
            xs = (rewards.T, dones.T, values.T, v_next.T)  # time-major
            _, advs = jax.lax.scan(step, jnp.zeros(rewards.shape[0]), xs,
                                   reverse=True)
            return advs.T  # back to [E, T]

        module = self.module

        def loss_fn(params, mb):
            logits, value = module.forward_train(params, mb["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, mb["actions"][:, None], axis=1)[:, 0]
            ratio = jnp.exp(logp - mb["logp_old"])
            adv = mb["advantages"]
            pg = -jnp.minimum(
                ratio * adv,
                jnp.clip(ratio, 1 - hp.clip_param, 1 + hp.clip_param) * adv)
            vf = 0.5 * jnp.square(value - mb["returns"])
            entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=1)
            loss = (pg.mean() + hp.vf_loss_coeff * vf.mean()
                    - hp.entropy_coeff * entropy.mean())
            return loss, {"policy_loss": pg.mean(), "vf_loss": vf.mean(),
                          "entropy": entropy.mean(),
                          "kl": (mb["logp_old"] - logp).mean()}

        def update(params, opt_state, batch, rng):
            E, T = batch["rewards"].shape
            advs = gae(batch["rewards"], batch["dones"], batch["values"],
                       batch["final_value"])
            rets = advs + batch["values"]
            flat = {
                "obs": batch["obs"].reshape(E * T, -1),
                "actions": batch["actions"].reshape(E * T),
                "logp_old": batch["logp"].reshape(E * T),
                "advantages": advs.reshape(E * T),
                "returns": rets.reshape(E * T),
            }
            a = flat["advantages"]
            flat["advantages"] = (a - a.mean()) / (a.std() + 1e-8)

            n = E * T
            mb = min(hp.minibatch_size, n)
            num_mb = max(1, n // mb)
            used = num_mb * mb

            def epoch_step(carry, key):
                params, opt_state = carry
                perm = jax.random.permutation(key, n)[:used]
                idx = perm.reshape(num_mb, mb)

                def mb_step(carry, rows):
                    params, opt_state = carry
                    mbatch = jax.tree_util.tree_map(
                        lambda x: x[rows], flat)
                    (_, metrics), grads = jax.value_and_grad(
                        loss_fn, has_aux=True)(params, mbatch)
                    updates, opt_state = self._tx.update(
                        grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state), metrics

                return jax.lax.scan(mb_step, (params, opt_state), idx)

            keys = jax.random.split(rng, hp.num_epochs)
            (params, opt_state), metrics = jax.lax.scan(
                epoch_step, (params, opt_state), keys)
            # Report the final epoch's mean metrics.
            metrics = jax.tree_util.tree_map(lambda m: m[-1].mean(), metrics)
            return params, opt_state, metrics

        return self._jit_update(
            update, num_state_args=2,
            batch_keys=("obs", "actions", "logp", "rewards", "dones",
                        "values", "final_value"))

    # -- public -------------------------------------------------------------
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One training iteration over a sampled batch.

        batch: obs [E,T,D], actions [E,T] int32, logp [E,T], rewards [E,T],
        dones [E,T], values [E,T], final_value [E].
        """
        self._rng, key = jax.random.split(self._rng)
        jbatch = self._shard_batch(
            {k: jnp.asarray(v) for k, v in batch.items()})
        self.params, self.opt_state, metrics = self._update(
            self.params, self.opt_state, jbatch, key)
        return {k: float(v) for k, v in metrics.items()}


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=PPO)
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 256
        self.grad_clip = 0.5

    def training(self, *, lr=None, gamma=None, lambda_=None,
                 clip_param=None, vf_loss_coeff=None, entropy_coeff=None,
                 num_epochs=None, minibatch_size=None, grad_clip=None,
                 **kwargs) -> "PPOConfig":
        for k, v in dict(lr=lr, gamma=gamma, lambda_=lambda_,
                         clip_param=clip_param,
                         vf_loss_coeff=vf_loss_coeff,
                         entropy_coeff=entropy_coeff,
                         num_epochs=num_epochs,
                         minibatch_size=minibatch_size,
                         grad_clip=grad_clip).items():
            if v is not None:
                setattr(self, k, v)
        return super().training(**kwargs)

    def hyperparams(self) -> PPOHyperparams:
        return PPOHyperparams(
            lr=self.lr, gamma=self.gamma, lambda_=self.lambda_,
            clip_param=self.clip_param, vf_loss_coeff=self.vf_loss_coeff,
            entropy_coeff=self.entropy_coeff, num_epochs=self.num_epochs,
            minibatch_size=self.minibatch_size, grad_clip=self.grad_clip)


class PPO(Algorithm):
    """ref: rllib/algorithms/ppo/ppo.py — training_step = sample rollouts
    from workers, one learner update, broadcast weights."""

    def _setup_learner(self, obs_dim: int, num_actions: int):
        cfg = self.config
        hp = cfg.hyperparams()
        seed, hidden = cfg.seed, cfg.model_hidden

        def factory(mesh=None):
            return PPOLearner(obs_dim, num_actions, hp, seed=seed,
                              mesh=mesh, hidden=hidden)

        return self._build_learner(factory)

    def training_step(self) -> Dict[str, float]:
        batch, episode_returns = self._sample_rollouts()
        metrics = self.learner.update(batch)
        self._broadcast_weights()
        if episode_returns:
            metrics["episode_return_mean"] = float(
                np.mean(episode_returns))
            metrics["episode_return_max"] = float(np.max(episode_returns))
            metrics["num_episodes"] = float(len(episode_returns))
        metrics["num_env_steps_sampled"] = float(
            batch["rewards"].size)
        return metrics
