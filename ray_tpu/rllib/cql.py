"""CQL: conservative Q-learning — offline RL for continuous control.

ref: rllib/algorithms/cql/cql.py:1 (SAC-based learner with the CQL(H)
conservative regularizer; trains from offline data only). TPU-first
shape: the whole update — SAC's twin-Q TD + actor + alpha steps PLUS
the conservative penalty (logsumexp over random/policy actions minus
dataset-action Q) — is one jitted program; dataset minibatches stream
from offline shards recorded via rllib.offline.

    algo = (CQLConfig().environment("Pendulum-v1")
            .offline_data(input_path=path).build())
    algo.train()          # no environment interaction
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm
from ray_tpu.rllib.sac import SACConfig, SACHyperparams, SACLearner


class CQLLearner(SACLearner):
    """SAC learner + conservative critic penalty (CQL(H), simplified:
    uniform + policy action samples, no importance correction — the
    variant the reference defaults to with `lagrangian=False`)."""

    def __init__(self, obs_dim: int, act_dim: int, hp: SACHyperparams,
                 *, cql_alpha: float = 1.0, cql_n_actions: int = 4,
                 seed: int = 0, hidden=(64, 64), mesh=None):
        self._cql_alpha = cql_alpha
        self._cql_n = cql_n_actions
        self._act_dim = act_dim
        super().__init__(obs_dim, act_dim, hp, seed=seed, hidden=hidden,
                         mesh=mesh)

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models import (
            apply_sac_actor,
            apply_twin_q,
            sample_squashed,
        )

        hp = self.hp
        cql_alpha = self._cql_alpha
        n_act = self._cql_n
        act_dim = self._act_dim

        def critic_loss_fn(critic, actor, target_critic, log_alpha,
                           batch, key):
            k_next, k_rand, k_pi = jax.random.split(key, 3)
            mu, log_std = apply_sac_actor(actor, batch["next_obs"])
            next_a, next_logp = sample_squashed(mu, log_std, k_next,
                                                hp.act_limit)
            tq1, tq2 = apply_twin_q(target_critic, batch["next_obs"],
                                    next_a)
            alpha = jnp.exp(log_alpha)
            next_v = jnp.minimum(tq1, tq2) - alpha * next_logp
            target = jax.lax.stop_gradient(
                batch["rewards"]
                + hp.gamma * (1.0 - batch["terminals"]) * next_v)
            q1, q2 = apply_twin_q(critic, batch["obs"], batch["actions"])
            td = ((q1 - target) ** 2 + (q2 - target) ** 2).mean()

            # Conservative penalty: push down Q on out-of-distribution
            # actions (logsumexp over sampled actions), push up on the
            # DATASET actions.
            B = batch["obs"].shape[0]
            rand_a = jax.random.uniform(
                k_rand, (n_act, B, act_dim),
                minval=-hp.act_limit, maxval=hp.act_limit)
            mu_c, std_c = apply_sac_actor(actor, batch["obs"])
            pi_keys = jax.random.split(k_pi, n_act)
            pi_a = jnp.stack([
                sample_squashed(mu_c, std_c, k, hp.act_limit)[0]
                for k in pi_keys])
            all_a = jnp.concatenate([rand_a, pi_a])        # [2n, B, d]

            def q_of(a):
                qa1, qa2 = apply_twin_q(critic, batch["obs"], a)
                return qa1, qa2

            qs1, qs2 = jax.vmap(q_of)(all_a)               # [2n, B]
            penalty = (
                (jax.scipy.special.logsumexp(qs1, axis=0) - q1).mean()
                + (jax.scipy.special.logsumexp(qs2, axis=0) - q2).mean())
            return td + cql_alpha * penalty, (td, penalty)

        def actor_loss_fn(actor, critic, log_alpha, batch, key):
            mu, log_std = apply_sac_actor(actor, batch["obs"])
            a, logp = sample_squashed(mu, log_std, key, hp.act_limit)
            q1, q2 = apply_twin_q(critic, batch["obs"], a)
            alpha = jax.lax.stop_gradient(jnp.exp(log_alpha))
            return (alpha * logp - jnp.minimum(q1, q2)).mean(), logp

        def alpha_loss_fn(log_alpha, logp):
            return -(log_alpha * jax.lax.stop_gradient(
                logp + hp.target_entropy)).mean()

        def update(actor, critic, target_critic, log_alpha,
                   actor_opt, critic_opt, alpha_opt, batch, key):
            k1, k2 = jax.random.split(key)
            (c_loss, (td, penalty)), c_grads = jax.value_and_grad(
                critic_loss_fn, has_aux=True)(
                critic, actor, target_critic, log_alpha, batch, k1)
            c_up, critic_opt = self._critic_tx.update(c_grads, critic_opt,
                                                      critic)
            critic = optax.apply_updates(critic, c_up)

            (a_loss, logp), a_grads = jax.value_and_grad(
                actor_loss_fn, has_aux=True)(actor, critic, log_alpha,
                                             batch, k2)
            a_up, actor_opt = self._actor_tx.update(a_grads, actor_opt,
                                                    actor)
            actor = optax.apply_updates(actor, a_up)

            al_loss, al_grad = jax.value_and_grad(alpha_loss_fn)(
                log_alpha, logp)
            al_up, alpha_opt = self._alpha_tx.update(al_grad, alpha_opt,
                                                     log_alpha)
            log_alpha = optax.apply_updates(log_alpha, al_up)

            target_critic = jax.tree_util.tree_map(
                lambda t, s: (1.0 - hp.tau) * t + hp.tau * s,
                target_critic, critic)
            metrics = {"critic_loss": td, "cql_penalty": penalty,
                       "actor_loss": a_loss, "alpha": jnp.exp(log_alpha),
                       "entropy": -logp.mean()}
            return (actor, critic, target_critic, log_alpha,
                    actor_opt, critic_opt, alpha_opt, metrics)

        # Same mesh wiring as the SAC parent: replicated state,
        # dp-sharded batch (plain jit when meshless).
        return self._jit_update(
            update, num_state_args=7,
            batch_keys=("obs", "actions", "rewards", "next_obs",
                        "terminals"))


class CQLConfig(SACConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = CQL
        self.cql_alpha = 1.0
        self.cql_n_actions = 4
        self.input_path = None

    def offline_data(self, *, input_path: str) -> "CQLConfig":
        self.input_path = input_path
        return self

    def training(self, *, cql_alpha=None, cql_n_actions=None,
                 **kwargs) -> "CQLConfig":
        if cql_alpha is not None:
            self.cql_alpha = cql_alpha
        if cql_n_actions is not None:
            self.cql_n_actions = cql_n_actions
        return super().training(**kwargs)


class CQL(Algorithm):
    """training_step: sample minibatches from the OFFLINE dataset only —
    the env exists solely for spaces + evaluation."""

    _eval_mode = "sac_mean"

    def _setup_learner(self, obs_dim: int, num_actions: int) -> CQLLearner:
        cfg: CQLConfig = self.config
        if not cfg.input_path:
            raise ValueError("CQLConfig.offline_data(input_path=...) first")
        info = self.space_info
        if not info["continuous"]:
            raise ValueError("CQL needs a continuous-control env")
        from ray_tpu.rllib.offline import read_samples

        rows = read_samples(cfg.input_path).take_all()
        self._data = {
            "obs": np.asarray([r["obs"] for r in rows], np.float32),
            "actions": np.asarray([r["actions"] for r in rows],
                                  np.float32),
            "rewards": np.asarray([r["rewards"] for r in rows],
                                  np.float32),
            "next_obs": np.asarray([r["next_obs"] for r in rows],
                                   np.float32),
            "terminals": np.asarray([r["terminals"] for r in rows],
                                    np.float32),
        }
        self._rng = np.random.default_rng(cfg.seed)
        hp = SACHyperparams(
            actor_lr=cfg.actor_lr, critic_lr=cfg.critic_lr,
            alpha_lr=cfg.alpha_lr, gamma=cfg.gamma, tau=cfg.tau,
            target_entropy=(cfg.target_entropy
                            if cfg.target_entropy is not None
                            else -float(info["act_dim"])),
            act_limit=info["act_limit"])
        act_dim, seed, hidden = info["act_dim"], cfg.seed, cfg.model_hidden
        alpha, n_act = cfg.cql_alpha, cfg.cql_n_actions

        def factory(mesh=None):
            return CQLLearner(obs_dim, act_dim, hp, cql_alpha=alpha,
                              cql_n_actions=n_act, seed=seed,
                              hidden=hidden, mesh=mesh)

        return self._build_learner(factory)

    def training_step(self) -> Dict[str, float]:
        cfg: CQLConfig = self.config
        n = len(self._data["obs"])
        agg: Dict[str, list] = {}
        for _ in range(cfg.num_updates_per_iteration):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            batch = {k: v[idx] for k, v in self._data.items()}
            for k, v in self.learner.update(batch).items():
                agg.setdefault(k, []).append(v)
        self._broadcast_weights()
        out = {k: float(np.mean(v)) for k, v in agg.items()}
        out["num_offline_rows"] = float(n)
        return out


def record_transitions(algo: Any, path: str, num_iterations: int = 4,
                       fmt: str = "parquet") -> str:
    """Record continuous-control transitions from a (SAC) algorithm's
    CURRENT behavior policy. Note: this yields NARROW (near-on-policy)
    data — the hardest offline-RL regime; prefer record_replay for CQL
    training sets."""
    from ray_tpu.rllib.offline import SampleWriter

    writer = SampleWriter(path, fmt=fmt)
    T = algo.config.rollout_fragment_length
    for _ in range(num_iterations):
        out = algo.workers[0].sample_transitions_continuous(T)
        writer.write(out["batch"])
    writer.close()
    return path


def record_replay(algo: Any, path: str, fmt: str = "parquet") -> str:
    """Dump an off-policy algorithm's REPLAY BUFFER as offline shards —
    diverse data spanning random warmup through the trained policy, the
    distribution offline methods are designed for (the D4RL-style
    'replay' datasets; measured here: CQL reaches better-than-behavior
    returns from a Pendulum replay dump, but oscillates near random on
    a narrow same-size expert-only set)."""
    from ray_tpu.rllib.offline import SampleWriter

    n = len(algo.replay)
    writer = SampleWriter(path, fmt=fmt)
    writer.write({k: v[:n] for k, v in algo.replay._store.items()})
    writer.close()
    return path
