"""DQN: double Q-learning with (prioritized) replay.

ref: rllib/algorithms/dqn/dqn.py (training_step: sample -> store ->
train from replay -> target sync) and dqn_rainbow_learner.py. TPU-first
shape: the TD update is one jitted program (double-DQN targets, Huber
loss, importance weighting) returning per-sample TD errors for the
priority write-back; the target network is a second param pytree synced
by assignment every `target_network_update_freq` updates.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.models import apply_mlp_q, init_mlp_q
from ray_tpu.rllib.replay_buffer import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)


@dataclasses.dataclass(frozen=True)
class DQNHyperparams:
    lr: float = 1e-3
    gamma: float = 0.99
    train_batch_size: int = 64
    num_updates_per_iteration: int = 16
    target_network_update_freq: int = 100    # in learner updates
    double_q: bool = True
    grad_clip: float = 10.0


class DQNLearner(Learner):
    """Ported onto the core Learner base (ref: learner.py:107): a mesh
    (from LearnerGroup's in-process SPMD mode) shards the batch over
    `dp` with replicated params — per-sample TD errors come back for
    prioritized-replay priorities in both modes."""

    _state_attrs = ("params", "target_params", "opt_state")

    def __init__(self, obs_dim: int, num_actions: int, hp: DQNHyperparams,
                 seed: int = 0, hidden=(64, 64), mesh=None):
        self.hp = hp
        self.mesh = mesh
        rng = jax.random.PRNGKey(seed)
        self.params = self._replicate(
            init_mlp_q(rng, obs_dim, num_actions, hidden))
        self.target_params = jax.tree_util.tree_map(jnp.copy, self.params)
        self._tx = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip),
            optax.adam(hp.lr),
        )
        self.opt_state = self._replicate(self._tx.init(self.params))
        self._updates = 0
        self._update = self._build_update()

    def _build_update(self):
        hp = self.hp

        def loss_fn(params, target_params, batch):
            q = apply_mlp_q(params, batch["obs"])
            q_sa = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=1)[:, 0]
            q_next_target = apply_mlp_q(target_params, batch["next_obs"])
            if hp.double_q:
                # Online net picks the argmax, target net evaluates it.
                q_next_online = apply_mlp_q(params, batch["next_obs"])
                next_a = jnp.argmax(q_next_online, axis=1)
            else:
                next_a = jnp.argmax(q_next_target, axis=1)
            next_q = jnp.take_along_axis(
                q_next_target, next_a[:, None], axis=1)[:, 0]
            target = (batch["rewards"]
                      + hp.gamma * (1.0 - batch["terminals"])
                      * jax.lax.stop_gradient(next_q))
            td = q_sa - target
            loss = jnp.mean(batch["weights"] * optax.huber_loss(td))
            return loss, td

        def update(params, target_params, opt_state, batch):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td

        # Donation diverges from the base convention ((0,2): target
        # params are NOT donated — they outlive the step), and the td
        # output stays dp-sharded for prioritized-replay priorities.
        return self._jit_update(
            update, num_state_args=3, has_rng=False, donate=(0, 2),
            batch_keys=("obs", "actions", "rewards", "next_obs",
                        "terminals", "weights"),
            out_spec=("rep", "rep", "rep", "dp"))

    def update(self, batch: Dict[str, np.ndarray]) -> tuple:
        jbatch = self._shard_batch(
            {k: jnp.asarray(v) for k, v in batch.items()
             if k != "batch_indexes"})
        self.params, self.opt_state, loss, td = self._update(
            self.params, self.target_params, self.opt_state, jbatch)
        self._updates += 1
        if self._updates % self.hp.target_network_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(jnp.copy,
                                                        self.params)
        return float(loss), np.asarray(td)

    def get_state(self) -> Dict[str, Any]:
        state = super().get_state()
        state["updates"] = self._updates   # plain int, not a pytree
        return state

    def set_state(self, state: Dict[str, Any]) -> None:
        super().set_state(state)
        self._updates = int(state.get("updates", self._updates))


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DQN)
        self.lr = 1e-3
        self.gamma = 0.99
        self.train_batch_size = 64
        self.num_updates_per_iteration = 16
        self.target_network_update_freq = 100
        self.double_q = True
        self.grad_clip = 10.0
        self.replay_buffer_capacity = 50_000
        self.prioritized_replay = True
        self.learning_starts = 500           # env steps before updates
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_iterations = 40

    def training(self, *, lr=None, gamma=None, train_batch_size=None,
                 num_updates_per_iteration=None,
                 target_network_update_freq=None, double_q=None,
                 grad_clip=None, replay_buffer_capacity=None,
                 prioritized_replay=None, learning_starts=None,
                 epsilon_initial=None, epsilon_final=None,
                 epsilon_decay_iterations=None, **kwargs) -> "DQNConfig":
        for k, v in dict(
                lr=lr, gamma=gamma, train_batch_size=train_batch_size,
                num_updates_per_iteration=num_updates_per_iteration,
                target_network_update_freq=target_network_update_freq,
                double_q=double_q, grad_clip=grad_clip,
                replay_buffer_capacity=replay_buffer_capacity,
                prioritized_replay=prioritized_replay,
                learning_starts=learning_starts,
                epsilon_initial=epsilon_initial,
                epsilon_final=epsilon_final,
                epsilon_decay_iterations=epsilon_decay_iterations).items():
            if v is not None:
                setattr(self, k, v)
        return super().training(**kwargs)

    def hyperparams(self) -> DQNHyperparams:
        return DQNHyperparams(
            lr=self.lr, gamma=self.gamma,
            train_batch_size=self.train_batch_size,
            num_updates_per_iteration=self.num_updates_per_iteration,
            target_network_update_freq=self.target_network_update_freq,
            double_q=self.double_q, grad_clip=self.grad_clip)


class DQN(Algorithm):
    """training_step: collect epsilon-greedy transitions into replay,
    run K sampled TD updates, write priorities back, broadcast."""

    _eval_mode = "greedy_q"

    def _setup_learner(self, obs_dim: int, num_actions: int) -> DQNLearner:
        cfg: DQNConfig = self.config
        if cfg.prioritized_replay:
            self.replay = PrioritizedReplayBuffer(
                cfg.replay_buffer_capacity, seed=cfg.seed)
        else:
            self.replay = ReplayBuffer(cfg.replay_buffer_capacity,
                                       seed=cfg.seed)
        self._env_steps = 0
        if getattr(cfg, "remote_learners", False) \
                and getattr(cfg, "num_learners", 0) > 0:
            raise ValueError(
                "DQN supports num_learners only in in-process mesh "
                "mode (remote actors would need ordered per-sample TD "
                "errors for prioritized replay)")
        hp, seed, hidden = cfg.hyperparams(), cfg.seed, cfg.model_hidden

        def factory(mesh=None):
            return DQNLearner(obs_dim, num_actions, hp, seed=seed,
                              hidden=hidden, mesh=mesh)

        return self._build_learner(factory)

    def _epsilon(self) -> float:
        cfg: DQNConfig = self.config
        frac = min(1.0, self._iteration / max(1,
                                              cfg.epsilon_decay_iterations))
        return (cfg.epsilon_initial
                + frac * (cfg.epsilon_final - cfg.epsilon_initial))

    def _collect(self, epsilon: float):
        T = self.config.rollout_fragment_length
        if self._remote:
            import ray_tpu

            outs = ray_tpu.get(
                [w.sample_transitions.remote(T, epsilon)
                 for w in self.workers], timeout=600)
        else:
            outs = [self.workers[0].sample_transitions(T, epsilon)]
        batch = {k: np.concatenate([o["batch"][k] for o in outs])
                 for k in outs[0]["batch"]}
        returns = [r for o in outs for r in o["episode_returns"]]
        return batch, returns

    def training_step(self) -> Dict[str, float]:
        cfg: DQNConfig = self.config
        eps = self._epsilon()
        batch, episode_returns = self._collect(eps)
        self.replay.add_batch(batch)
        self._env_steps += len(batch["rewards"])

        metrics: Dict[str, float] = {"epsilon": eps}
        if self._env_steps >= cfg.learning_starts and len(self.replay) \
                >= cfg.train_batch_size:
            losses = []
            for _ in range(cfg.num_updates_per_iteration):
                sample = self.replay.sample(cfg.train_batch_size)
                loss, td = self.learner.update(sample)
                self.replay.update_priorities(sample["batch_indexes"], td)
                losses.append(loss)
            metrics["loss"] = float(np.mean(losses))
            self._broadcast_weights()
        if episode_returns:
            metrics["episode_return_mean"] = float(
                np.mean(episode_returns))
            metrics["num_episodes"] = float(len(episode_returns))
        metrics["num_env_steps_sampled"] = float(self._env_steps)
        metrics["replay_size"] = float(len(self.replay))
        return metrics
