"""Policy/value networks as pure-JAX pytrees (the RLModule analogue,
ref: rllib/core/rl_module/). Kept framework-free like the rest of
ray_tpu/models: params are nested dicts, apply is a pure function —
trivially shardable/donatable under jit."""
from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]


def init_mlp_policy(rng: jax.Array, obs_dim: int, num_actions: int,
                    hidden: Sequence[int] = (64, 64)) -> Params:
    """Separate pi/v MLP towers (shared trunks hurt small-control tasks)."""
    params: Params = {}
    for tower, out_dim in (("pi", num_actions), ("v", 1)):
        sizes = [obs_dim, *hidden, out_dim]
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            rng, key = jax.random.split(rng)
            scale = jnp.sqrt(2.0 / fan_in)
            if i == len(sizes) - 2:  # small final layer: near-uniform policy
                scale = scale * 0.01
            params[f"{tower}_w{i}"] = (
                jax.random.normal(key, (fan_in, fan_out)) * scale)
            params[f"{tower}_b{i}"] = jnp.zeros((fan_out,))
    return params


def apply_mlp_policy(params: Params, obs: jnp.ndarray
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""
    def tower(prefix: str, x: jnp.ndarray) -> jnp.ndarray:
        i = 0
        while f"{prefix}_w{i}" in params:
            x = x @ params[f"{prefix}_w{i}"] + params[f"{prefix}_b{i}"]
            if f"{prefix}_w{i + 1}" in params:
                x = jnp.tanh(x)
            i += 1
        return x

    logits = tower("pi", obs)
    value = tower("v", obs)[..., 0]
    return logits, value


def init_mlp_q(rng: jax.Array, obs_dim: int, num_actions: int,
               hidden: Sequence[int] = (64, 64)) -> Params:
    """Q-network MLP: obs -> Q(s, .) (the DQN RLModule analogue)."""
    params: Params = {}
    sizes = [obs_dim, *hidden, num_actions]
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, key = jax.random.split(rng)
        scale = jnp.sqrt(2.0 / fan_in)
        params[f"q_w{i}"] = jax.random.normal(key, (fan_in, fan_out)) * scale
        params[f"q_b{i}"] = jnp.zeros((fan_out,))
    return params


def apply_mlp_q(params: Params, obs: jnp.ndarray) -> jnp.ndarray:
    """obs [B, obs_dim] -> Q [B, A]."""
    x = obs
    i = 0
    while f"q_w{i}" in params:
        x = x @ params[f"q_w{i}"] + params[f"q_b{i}"]
        if f"q_w{i + 1}" in params:
            x = jnp.tanh(x)
        i += 1
    return x
