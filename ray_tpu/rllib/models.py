"""Policy/value networks as pure-JAX pytrees (the RLModule analogue,
ref: rllib/core/rl_module/). Kept framework-free like the rest of
ray_tpu/models: params are nested dicts, apply is a pure function —
trivially shardable/donatable under jit."""
from __future__ import annotations

from typing import Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]


def init_mlp_policy(rng: jax.Array, obs_dim: int, num_actions: int,
                    hidden: Sequence[int] = (64, 64)) -> Params:
    """Separate pi/v MLP towers (shared trunks hurt small-control tasks)."""
    params: Params = {}
    for tower, out_dim in (("pi", num_actions), ("v", 1)):
        sizes = [obs_dim, *hidden, out_dim]
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            rng, key = jax.random.split(rng)
            scale = jnp.sqrt(2.0 / fan_in)
            if i == len(sizes) - 2:  # small final layer: near-uniform policy
                scale = scale * 0.01
            params[f"{tower}_w{i}"] = (
                jax.random.normal(key, (fan_in, fan_out)) * scale)
            params[f"{tower}_b{i}"] = jnp.zeros((fan_out,))
    return params


def apply_mlp_policy(params: Params, obs: jnp.ndarray
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""
    def tower(prefix: str, x: jnp.ndarray) -> jnp.ndarray:
        i = 0
        while f"{prefix}_w{i}" in params:
            x = x @ params[f"{prefix}_w{i}"] + params[f"{prefix}_b{i}"]
            if f"{prefix}_w{i + 1}" in params:
                x = jnp.tanh(x)
            i += 1
        return x

    logits = tower("pi", obs)
    value = tower("v", obs)[..., 0]
    return logits, value


def _init_mlp(rng: jax.Array, prefix: str, sizes: Sequence[int],
              params: Params, final_scale: float = 1.0) -> jax.Array:
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, key = jax.random.split(rng)
        scale = jnp.sqrt(2.0 / fan_in)
        if i == len(sizes) - 2:
            scale = scale * final_scale
        params[f"{prefix}_w{i}"] = (
            jax.random.normal(key, (fan_in, fan_out)) * scale)
        params[f"{prefix}_b{i}"] = jnp.zeros((fan_out,))
    return rng


def _apply_mlp(params: Params, prefix: str, x: jnp.ndarray) -> jnp.ndarray:
    i = 0
    while f"{prefix}_w{i}" in params:
        x = x @ params[f"{prefix}_w{i}"] + params[f"{prefix}_b{i}"]
        if f"{prefix}_w{i + 1}" in params:
            x = jnp.tanh(x)
        i += 1
    return x


LOG_STD_MIN, LOG_STD_MAX = -20.0, 2.0


def init_sac_actor(rng: jax.Array, obs_dim: int, act_dim: int,
                   hidden: Sequence[int] = (64, 64)) -> Params:
    """Squashed-Gaussian policy head: obs -> (mu, log_std) [B, 2*act_dim]
    (ref: rllib/algorithms/sac — SquashedGaussian action dist)."""
    params: Params = {}
    _init_mlp(rng, "actor", [obs_dim, *hidden, 2 * act_dim], params,
              final_scale=0.01)
    return params


def apply_sac_actor(params: Params, obs: jnp.ndarray
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    out = _apply_mlp(params, "actor", obs)
    mu, log_std = jnp.split(out, 2, axis=-1)
    return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def squashed_logp(pre: jnp.ndarray, mu: jnp.ndarray,
                  log_std: jnp.ndarray) -> jnp.ndarray:
    """log-prob of a = tanh(pre) under Normal(mu, exp(log_std)) with the
    tanh change-of-variables correction; the softplus form of
    log det tanh' = sum log(1 - tanh²) is the numerically stable one."""
    std = jnp.exp(log_std)
    logp_gauss = (-0.5 * ((pre - mu) / std) ** 2 - log_std
                  - 0.5 * jnp.log(2.0 * jnp.pi)).sum(-1)
    return logp_gauss - (2.0 * (jnp.log(2.0) - pre
                                - jax.nn.softplus(-2.0 * pre))).sum(-1)


def sample_squashed(mu: jnp.ndarray, log_std: jnp.ndarray, key: jax.Array,
                    act_limit: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reparameterized tanh-squashed sample + its log-prob."""
    pre = mu + jnp.exp(log_std) * jax.random.normal(key, mu.shape)
    return jnp.tanh(pre) * act_limit, squashed_logp(pre, mu, log_std)


def init_twin_q(rng: jax.Array, obs_dim: int, act_dim: int,
                hidden: Sequence[int] = (64, 64)) -> Params:
    """Two independent continuous Q towers (clipped double-Q)."""
    params: Params = {}
    rng = _init_mlp(rng, "q1", [obs_dim + act_dim, *hidden, 1], params)
    _init_mlp(rng, "q2", [obs_dim + act_dim, *hidden, 1], params)
    return params


def apply_twin_q(params: Params, obs: jnp.ndarray, act: jnp.ndarray
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    x = jnp.concatenate([obs, act], axis=-1)
    return (_apply_mlp(params, "q1", x)[..., 0],
            _apply_mlp(params, "q2", x)[..., 0])


def init_mlp_q(rng: jax.Array, obs_dim: int, num_actions: int,
               hidden: Sequence[int] = (64, 64)) -> Params:
    """Q-network MLP: obs -> Q(s, .) (the DQN RLModule analogue)."""
    params: Params = {}
    sizes = [obs_dim, *hidden, num_actions]
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        rng, key = jax.random.split(rng)
        scale = jnp.sqrt(2.0 / fan_in)
        params[f"q_w{i}"] = jax.random.normal(key, (fan_in, fan_out)) * scale
        params[f"q_b{i}"] = jnp.zeros((fan_out,))
    return params


def apply_mlp_q(params: Params, obs: jnp.ndarray) -> jnp.ndarray:
    """obs [B, obs_dim] -> Q [B, A]."""
    x = obs
    i = 0
    while f"q_w{i}" in params:
        x = x @ params[f"q_w{i}"] + params[f"q_b{i}"]
        if f"q_w{i + 1}" in params:
            x = jnp.tanh(x)
        i += 1
    return x
