"""DreamerV3: model-based RL with a categorical-latent world model.

ref: rllib/algorithms/dreamerv3/ (the reference's torch/tf port of
Hafner et al. 2023, "Mastering Diverse Domains through World Models") —
RSSM world model (sequence GRU + categorical latents), actor and critic
trained entirely on imagined rollouts, symlog predictions with two-hot
reward/value heads, percentile return normalization, EMA-regularized
critic.

TPU-first shape: the ENTIRE training iteration — world-model scan over
the replay window, H-step imagination scan, all three optimizers, the
slow-critic EMA, the return-scale EMA — is ONE jitted program built on
the core Learner base (`rllib/core/learner.py`). The recurrent pieces
are `lax.scan`s (no Python-loop unrolling), so the program stays one
XLA computation with static shapes; under a mesh the replay batch
shards over `dp` like every other learner here.

Collection diverges from the other algorithms' stateless RolloutWorker:
the policy is recurrent (posterior state carried across env steps), so
DreamerV3 owns its env stepping with a jitted recurrent policy step —
the same split the reference makes (DreamerV3 has its own EnvRunner,
rllib/algorithms/dreamerv3/utils/env_runner.py).

Discrete actions use a categorical actor trained with REINFORCE over a
stop-gradded imagined rollout (the paper's discrete estimator);
continuous actions use a tanh-squashed Gaussian trained by DYNAMICS
BACKPROP — the rollout stays differentiable and reparameterized action
samples carry gradients through the GRU/prior/heads into the lambda
returns (the paper's continuous estimator). Replay uses on-arrival
records: a step's `reward`/`cont`
describe ARRIVING at its observation, `prev_action` is the action that
led there — terminal observations are stored (cont=0), auto-reset
starts carry `is_first=1`.

Validated on CPU at small capacity (deter 128, 8x8 latents): CartPole
returns 22 -> 457 (best) in 160 iterations; Pendulum -1292 -> -236
(best 5-iteration window) in 500 iterations via dynamics backprop —
the REINFORCE estimator does NOT learn Pendulum, which is why the
continuous path differentiates through the rollout.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.env import VectorEnv, make_env
from ray_tpu.rllib.models import Params, _apply_mlp, _init_mlp
from ray_tpu.rllib.replay_buffer import SequenceReplayBuffer

# ---------------------------------------------------------------------------
# symlog / two-hot (Hafner et al. 2023 §"Robust predictions")
# ---------------------------------------------------------------------------


def symlog(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def twohot(y: jnp.ndarray, bins: jnp.ndarray) -> jnp.ndarray:
    """Scalar y (any shape) -> distribution over `bins` [K] putting mass
    on the two neighbours proportionally to proximity (exact expectation
    preservation for in-range y; clamped at the edges)."""
    k = jnp.clip(jnp.searchsorted(bins, y), 1, bins.shape[0] - 1)
    lo, hi = bins[k - 1], bins[k]
    w_hi = jnp.clip((y - lo) / (hi - lo), 0.0, 1.0)
    return (jax.nn.one_hot(k - 1, bins.shape[0]) * (1.0 - w_hi)[..., None]
            + jax.nn.one_hot(k, bins.shape[0]) * w_hi[..., None])


def twohot_decode(logits: jnp.ndarray, bins: jnp.ndarray) -> jnp.ndarray:
    return (jax.nn.softmax(logits, axis=-1) * bins).sum(-1)


# ---------------------------------------------------------------------------
# hyperparams
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DreamerV3Hyperparams:
    deter_dim: int = 256
    num_categoricals: int = 16
    num_classes: int = 16
    units: int = 256            # width of every MLP (2 hidden layers)
    num_bins: int = 41          # two-hot bins for reward/value, symlog space
    batch_size: int = 16
    batch_length: int = 16
    horizon: int = 15
    gamma: float = 0.997
    lam: float = 0.95
    unimix: float = 0.01
    free_bits: float = 1.0
    kl_dyn_scale: float = 0.5
    kl_rep_scale: float = 0.1
    ent_coef: float = 3e-4
    lr_world: float = 1e-3
    lr_actor: float = 3e-4
    lr_critic: float = 3e-4
    grad_clip: float = 100.0
    return_norm_decay: float = 0.99
    slow_critic_decay: float = 0.98
    slow_reg_scale: float = 1.0

    @property
    def stoch_dim(self) -> int:
        return self.num_categoricals * self.num_classes

    @property
    def feat_dim(self) -> int:
        return self.deter_dim + self.stoch_dim


@dataclasses.dataclass(frozen=True)
class ActSpec:
    """Action-space description. `n` is the action count (discrete) or
    the action dimension (continuous); continuous actions live in
    [-limit, limit]^n and are fed to the networks normalized to
    [-1, 1]."""

    kind: str            # "discrete" | "continuous"
    n: int
    limit: float = 1.0

    @property
    def input_dim(self) -> int:
        """Width of the action input to the sequence model."""
        return self.n

    @property
    def actor_out_dim(self) -> int:
        return self.n if self.kind == "discrete" else 2 * self.n


# ---------------------------------------------------------------------------
# networks (pure-pytree params, models.py conventions)
# ---------------------------------------------------------------------------


def _init_gru(rng: jax.Array, prefix: str, in_dim: int, hid: int,
              params: Params) -> jax.Array:
    for gate in ("r", "z", "n"):
        rng, key = jax.random.split(rng)
        params[f"{prefix}_w{gate}"] = jax.random.normal(
            key, (in_dim + hid, hid)) * jnp.sqrt(1.0 / (in_dim + hid))
        params[f"{prefix}_b{gate}"] = jnp.zeros((hid,))
    return rng


def _apply_gru(params: Params, prefix: str, h: jnp.ndarray,
               x: jnp.ndarray) -> jnp.ndarray:
    hx = jnp.concatenate([h, x], -1)
    r = jax.nn.sigmoid(hx @ params[f"{prefix}_wr"] + params[f"{prefix}_br"])
    z = jax.nn.sigmoid(hx @ params[f"{prefix}_wz"] + params[f"{prefix}_bz"])
    rx = jnp.concatenate([r * h, x], -1)
    n = jnp.tanh(rx @ params[f"{prefix}_wn"] + params[f"{prefix}_bn"])
    return (1.0 - z) * n + z * h


def init_world_model(rng: jax.Array, obs_dim: int, act_in_dim: int,
                     hp: DreamerV3Hyperparams) -> Params:
    p: Params = {}
    u, d, s = hp.units, hp.deter_dim, hp.stoch_dim
    rng = _init_mlp(rng, "enc", [obs_dim, u, u], p)
    rng = _init_gru(rng, "gru", s + act_in_dim, d, p)
    rng = _init_mlp(rng, "prior", [d, u, s], p)
    rng = _init_mlp(rng, "post", [d + u, u, s], p)
    rng = _init_mlp(rng, "dec", [hp.feat_dim, u, u, obs_dim], p)
    rng = _init_mlp(rng, "rew", [hp.feat_dim, u, u, hp.num_bins], p,
                    final_scale=0.0)   # zero-init: predict 0 at start
    _init_mlp(rng, "cont", [hp.feat_dim, u, u, 1], p)
    return p


def init_actor(rng: jax.Array, out_dim: int,
               hp: DreamerV3Hyperparams) -> Params:
    p: Params = {}
    _init_mlp(rng, "actor", [hp.feat_dim, hp.units, hp.units, out_dim],
              p, final_scale=0.01)
    return p


LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0


def _actor_dist(out: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Continuous actor head -> (mu, clipped log_std). The ONE place
    the parameterization lives — imagination, acting, and the loss all
    decode through here so they can never sample from one distribution
    and score under another."""
    mu, log_std = jnp.split(out, 2, -1)
    return mu, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)


def init_critic(rng: jax.Array, hp: DreamerV3Hyperparams) -> Params:
    p: Params = {}
    _init_mlp(rng, "critic", [hp.feat_dim, hp.units, hp.units, hp.num_bins],
              p, final_scale=0.0)
    return p


def _mixed_probs(logits: jnp.ndarray, hp: DreamerV3Hyperparams
                 ) -> jnp.ndarray:
    """1% uniform mix keeps every class reachable (bounds the KL)."""
    probs = jax.nn.softmax(logits, -1)
    return (1.0 - hp.unimix) * probs + hp.unimix / hp.num_classes


def _sample_latent(logits: jnp.ndarray, key: jax.Array,
                   hp: DreamerV3Hyperparams) -> jnp.ndarray:
    """Straight-through one-hot sample from [.., ncat, ncls] logits."""
    probs = _mixed_probs(logits, hp)
    idx = jax.random.categorical(key, jnp.log(probs), axis=-1)
    onehot = jax.nn.one_hot(idx, hp.num_classes, dtype=probs.dtype)
    return onehot + probs - jax.lax.stop_gradient(probs)


def _kl_cat(p_logits: jnp.ndarray, q_logits: jnp.ndarray,
            hp: DreamerV3Hyperparams) -> jnp.ndarray:
    """KL(p || q) summed over categoricals -> [...] (batch dims)."""
    p = _mixed_probs(p_logits, hp)
    q = _mixed_probs(q_logits, hp)
    return (p * (jnp.log(p) - jnp.log(q))).sum((-2, -1))


# ---------------------------------------------------------------------------
# learner
# ---------------------------------------------------------------------------


class DreamerV3Learner(Learner):
    """World model + actor + critic in one fused jitted update."""

    _state_attrs = ("wm_params", "actor_params", "critic_params",
                    "slow_critic", "wm_opt", "actor_opt", "critic_opt",
                    "return_scale", "_rng")

    def __init__(self, obs_dim: int, act_spec: "ActSpec | int",
                 hp: DreamerV3Hyperparams, seed: int = 0, mesh=None):
        if isinstance(act_spec, int):  # convenience: N discrete actions
            act_spec = ActSpec("discrete", act_spec)
        self.hp = hp
        self.mesh = mesh
        self.obs_dim = obs_dim
        self.act_spec = act_spec
        self.bins = jnp.linspace(-20.0, 20.0, hp.num_bins)  # symlog space
        rng = jax.random.PRNGKey(seed)
        k_wm, k_actor, k_critic, self._rng = jax.random.split(rng, 4)
        self.wm_params = self._replicate(
            init_world_model(k_wm, obs_dim, act_spec.input_dim, hp))
        self.actor_params = self._replicate(
            init_actor(k_actor, act_spec.actor_out_dim, hp))
        self.critic_params = self._replicate(init_critic(k_critic, hp))
        self.slow_critic = jax.tree_util.tree_map(jnp.copy,
                                                  self.critic_params)
        self._wm_tx = optax.chain(optax.clip_by_global_norm(hp.grad_clip),
                                  optax.adam(hp.lr_world))
        self._actor_tx = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip),
            optax.adam(hp.lr_actor))
        self._critic_tx = optax.chain(
            optax.clip_by_global_norm(hp.grad_clip),
            optax.adam(hp.lr_critic))
        self.wm_opt = self._replicate(self._wm_tx.init(self.wm_params))
        self.actor_opt = self._replicate(
            self._actor_tx.init(self.actor_params))
        self.critic_opt = self._replicate(
            self._critic_tx.init(self.critic_params))
        # EMA of percentile(R,95)-percentile(R,5): advantage denominator.
        self.return_scale = self._replicate(jnp.ones(()))
        self._update = self._build_update()
        self._policy_step = jax.jit(self._policy_step_fn,
                                    static_argnames=("greedy",))

    # The rollout/eval side needs both wm and actor.
    def get_weights(self) -> Any:
        return jax.device_get({"wm": self.wm_params,
                               "actor": self.actor_params})

    def set_weights(self, weights: Any) -> None:
        self.wm_params = self._replicate(weights["wm"])
        self.actor_params = self._replicate(weights["actor"])

    # -- model pieces ---------------------------------------------------
    def _act_input(self, a: jnp.ndarray) -> jnp.ndarray:
        """Action(s) -> sequence-model input: one-hot for discrete,
        the normalized [-1, 1] vector unchanged for continuous."""
        if self.act_spec.kind == "discrete":
            return jax.nn.one_hot(a, self.act_spec.n)
        return a

    def _observe(self, wm: Params, batch: Dict[str, jnp.ndarray],
                 key: jax.Array) -> Tuple[jnp.ndarray, ...]:
        """RSSM posterior scan over the [B, L] window (time-major
        internally). Returns feats [B, L, F] + prior/post logits."""
        hp = self.hp
        B, L = batch["obs"].shape[:2]
        embed = _apply_mlp(wm, "enc", symlog(batch["obs"]))      # [B,L,U]
        prev_a = self._act_input(batch["prev_action"])
        # time-major for the scan
        embed_t = jnp.swapaxes(embed, 0, 1)
        prev_a_t = jnp.swapaxes(prev_a, 0, 1)
        first_t = jnp.swapaxes(batch["is_first"].astype(jnp.float32), 0, 1)
        keys = jax.random.split(key, L)

        def step(carry, xs):
            h, z = carry
            emb, pa, first, k = xs
            keep = (1.0 - first)[:, None]
            h = h * keep
            z = z * keep[..., None]
            pa = pa * keep
            h = _apply_gru(wm, "gru",
                           h, jnp.concatenate(
                               [z.reshape(B, -1), pa], -1))
            prior_logits = _apply_mlp(wm, "prior", h).reshape(
                B, hp.num_categoricals, hp.num_classes)
            post_logits = _apply_mlp(
                wm, "post", jnp.concatenate([h, emb], -1)).reshape(
                    B, hp.num_categoricals, hp.num_classes)
            z = _sample_latent(post_logits, k, hp)
            return (h, z), (h, z, prior_logits, post_logits)

        h0 = jnp.zeros((B, hp.deter_dim))
        z0 = jnp.zeros((B, hp.num_categoricals, hp.num_classes))
        _, (hs, zs, priors, posts) = jax.lax.scan(
            step, (h0, z0), (embed_t, prev_a_t, first_t, keys))
        hs = jnp.swapaxes(hs, 0, 1)                    # [B,L,D]
        zs = jnp.swapaxes(zs, 0, 1)                    # [B,L,ncat,ncls]
        feats = jnp.concatenate([hs, zs.reshape(B, L, -1)], -1)
        return feats, hs, zs, jnp.swapaxes(priors, 0, 1), \
            jnp.swapaxes(posts, 0, 1)

    def _imagine(self, wm: Params, actor: Params, h0, z0, key
                 ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Roll the prior H steps with actor actions. h0/z0: [N, ...]
        flattened posterior starts (stop-gradded by the caller).

        Gradient contract: DISCRETE returns stop-gradded feats/actions
        (REINFORCE re-scores the samples); CONTINUOUS returns the LIVE
        graph — reparameterized actions flow through GRU/prior into the
        feats, which is the whole dynamics-backprop estimator. Don't
        add a stop_gradient on the continuous path."""
        hp = self.hp
        N = h0.shape[0]

        def step(carry, k):
            h, z = carry
            feat = jnp.concatenate([h, z.reshape(N, -1)], -1)
            ka, kz = jax.random.split(k)
            out = _apply_mlp(actor, "actor", feat)
            if self.act_spec.kind == "discrete":
                a = jax.random.categorical(ka, out, axis=-1)
                a_in = jax.nn.one_hot(a, self.act_spec.n)
                a_rec = a          # action index, for the logp lookup
            else:
                mu, log_std = _actor_dist(out)
                pre = mu + jnp.exp(log_std) * jax.random.normal(
                    ka, mu.shape)          # reparameterized
                a_in = jnp.tanh(pre)
                # The continuous loss differentiates through the
                # rollout itself (dynamics backprop) — the recorded
                # samples are diagnostics, not a REINFORCE input.
                a_rec = pre
            h = _apply_gru(wm, "gru", h,
                           jnp.concatenate([z.reshape(N, -1), a_in], -1))
            prior_logits = _apply_mlp(wm, "prior", h).reshape(
                N, hp.num_categoricals, hp.num_classes)
            z = _sample_latent(prior_logits, kz, hp)
            return (h, z), (feat, a_rec)

        keys = jax.random.split(key, hp.horizon)
        (h, z), (feats, actions) = jax.lax.scan(step, (h0, z0), keys)
        last = jnp.concatenate([h, z.reshape(N, -1)], -1)[None]
        feats = jnp.concatenate([feats, last], 0)      # [H+1, N, F]
        if self.act_spec.kind == "discrete":
            # REINFORCE: the rollout itself carries no actor gradient.
            return (jax.lax.stop_gradient(feats),
                    jax.lax.stop_gradient(actions))
        # Continuous: keep the graph — the actor trains by dynamics
        # backprop (reparameterized actions -> GRU/prior/heads ->
        # returns), the paper's gradient estimator for continuous
        # control. Straight-through latent samples pass gradients too.
        return feats, actions

    # -- fused update ---------------------------------------------------
    def _build_update(self):
        hp = self.hp
        bins = self.bins

        def wm_loss_fn(wm, batch, key):
            feats, hs, zs, priors, posts = self._observe(wm, batch, key)
            obs_hat = _apply_mlp(wm, "dec", feats)
            recon = ((obs_hat - symlog(batch["obs"])) ** 2).sum(-1)
            rew_logits = _apply_mlp(wm, "rew", feats)
            rew_target = twohot(symlog(batch["reward"]), bins)
            rew_loss = -(rew_target
                         * jax.nn.log_softmax(rew_logits, -1)).sum(-1)
            cont_logit = _apply_mlp(wm, "cont", feats)[..., 0]
            cont = batch["cont"].astype(jnp.float32)
            cont_loss = optax.sigmoid_binary_cross_entropy(cont_logit, cont)
            dyn = jnp.maximum(hp.free_bits, _kl_cat(
                jax.lax.stop_gradient(posts), priors, hp))
            rep = jnp.maximum(hp.free_bits, _kl_cat(
                posts, jax.lax.stop_gradient(priors), hp))
            loss = jnp.mean(recon + rew_loss + cont_loss
                            + hp.kl_dyn_scale * dyn + hp.kl_rep_scale * rep)
            aux = {"hs": hs, "zs": zs,
                   "recon": recon.mean(), "rew_loss": rew_loss.mean(),
                   "cont_loss": cont_loss.mean(), "kl_dyn": dyn.mean()}
            return loss, aux

        def update(wm, actor, critic, slow_critic, wm_opt, actor_opt,
                   critic_opt, scale, batch, rng):
            k_wm, k_img = jax.random.split(rng)
            (wm_loss, aux), wm_grads = jax.value_and_grad(
                wm_loss_fn, has_aux=True)(wm, batch, k_wm)
            wm_updates, wm_opt = self._wm_tx.update(wm_grads, wm_opt, wm)
            wm = optax.apply_updates(wm, wm_updates)

            # ---- imagination from every posterior state (post-update
            # world model; starts are stop-grads)
            B, L = batch["obs"].shape[:2]
            N = B * L
            h0 = jax.lax.stop_gradient(
                aux.pop("hs").reshape(N, -1))
            z0 = jax.lax.stop_gradient(
                aux.pop("zs").reshape(N, hp.num_categoricals,
                                      hp.num_classes))

            def rollout_scalars(feats):
                """World-model heads + lambda returns + weights along an
                imagined trajectory (carries actor gradients when feats
                do)."""
                rewards = symexp(twohot_decode(
                    _apply_mlp(wm, "rew", feats[1:]), bins))      # [H,N]
                conts = jax.nn.sigmoid(
                    _apply_mlp(wm, "cont", feats[1:])[..., 0])    # [H,N]
                values = symexp(twohot_decode(
                    _apply_mlp(critic, "critic", feats), bins))   # [H+1,N]

                def ret_step(nxt, xs):
                    r, c, v_next = xs
                    ret = r + hp.gamma * c * ((1.0 - hp.lam) * v_next
                                              + hp.lam * nxt)
                    return ret, ret

                _, returns = jax.lax.scan(
                    ret_step, values[-1],
                    (rewards[::-1], conts[::-1], values[1:][::-1]))
                returns = returns[::-1]                           # [H,N]
                # trajectory weights: prob the rollout is alive ENTERING
                # each state (terminals cut future losses)
                w = jax.lax.stop_gradient(jnp.concatenate(
                    [jnp.ones((1, N)), jnp.cumprod(conts[:-1], 0)], 0))
                return returns, values, w

            def actor_loss_fn(actor_p):
                # The rollout runs INSIDE the actor grad: for continuous
                # actions it is differentiable (dynamics backprop, the
                # paper's continuous-control estimator); for discrete it
                # is stop-gradded and REINFORCE scores the samples.
                feats, actions = self._imagine(wm, actor_p, h0, z0,
                                               k_img)
                returns, values, w = rollout_scalars(feats)
                # return normalization: EMA of the 5th..95th percentile
                # range (no gradient through the normalizer)
                sg_ret = jax.lax.stop_gradient(returns)
                span = (jnp.percentile(sg_ret, 95)
                        - jnp.percentile(sg_ret, 5))
                scale_new = (hp.return_norm_decay * scale
                             + (1.0 - hp.return_norm_decay) * span)
                inv = 1.0 / jnp.maximum(1.0, scale_new)
                out = _apply_mlp(actor_p, "actor", feats[:-1])
                if self.act_spec.kind == "discrete":
                    logp = jax.nn.log_softmax(out, -1)
                    probs = jax.nn.softmax(out, -1)
                    taken = jnp.take_along_axis(
                        logp, actions[..., None], -1)[..., 0]  # [H,N]
                    entropy = -(probs * logp).sum(-1)
                    adv = jax.lax.stop_gradient(
                        (returns - values[:-1]) * inv)
                    loss = -(w * (adv * taken
                                  + hp.ent_coef * entropy)).mean()
                else:
                    mu, log_std = _actor_dist(out)
                    # Gaussian entropy (the tanh correction adds no
                    # useful gradient to the bonus).
                    entropy = (log_std
                               + 0.5 * jnp.log(2.0 * jnp.pi * jnp.e)
                               ).sum(-1)
                    # dynamics backprop: maximize normalized lambda
                    # returns directly through the rollout
                    loss = -(w * (returns * inv
                                  + hp.ent_coef * entropy)).mean()
                saved = {"feats": jax.lax.stop_gradient(feats),
                         "returns": sg_ret, "w": w,
                         "scale_new": scale_new,
                         "entropy": entropy.mean()}
                return loss, saved

            (actor_loss, saved), actor_grads = jax.value_and_grad(
                actor_loss_fn, has_aux=True)(actor)
            actor_updates, actor_opt = self._actor_tx.update(
                actor_grads, actor_opt, actor)
            actor = optax.apply_updates(actor, actor_updates)
            feats, returns, w = (saved["feats"], saved["returns"],
                                 saved["w"])
            scale = saved["scale_new"]

            ret_target = twohot(symlog(returns), bins)        # [H,N,K]
            slow_probs = jax.lax.stop_gradient(jax.nn.softmax(
                _apply_mlp(slow_critic, "critic", feats[:-1]), -1))

            def critic_loss_fn(critic_p):
                logits = _apply_mlp(critic_p, "critic", feats[:-1])
                logp = jax.nn.log_softmax(logits, -1)
                ce = -(ret_target * logp).sum(-1)
                reg = -(slow_probs * logp).sum(-1) * hp.slow_reg_scale
                return (w * (ce + reg)).mean()

            critic_loss, critic_grads = jax.value_and_grad(
                critic_loss_fn)(critic)
            critic_updates, critic_opt = self._critic_tx.update(
                critic_grads, critic_opt, critic)
            critic = optax.apply_updates(critic, critic_updates)
            slow_critic = jax.tree_util.tree_map(
                lambda s, c: hp.slow_critic_decay * s
                + (1.0 - hp.slow_critic_decay) * c,
                slow_critic, critic)

            metrics = {
                "world_model_loss": wm_loss,
                "recon_loss": aux["recon"], "reward_loss": aux["rew_loss"],
                "cont_loss": aux["cont_loss"], "kl_dyn": aux["kl_dyn"],
                "actor_loss": actor_loss, "critic_loss": critic_loss,
                "entropy": saved["entropy"], "return_scale": scale,
                "imagined_return_mean": returns.mean(),
            }
            return (wm, actor, critic, slow_critic, wm_opt, actor_opt,
                    critic_opt, scale, metrics)

        return self._jit_update(
            update, num_state_args=8,
            batch_keys=("obs", "prev_action", "reward", "is_first",
                        "cont"))

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        self._rng, key = jax.random.split(self._rng)
        jbatch = self._shard_batch(
            {k: jnp.asarray(v) for k, v in batch.items()})
        (self.wm_params, self.actor_params, self.critic_params,
         self.slow_critic, self.wm_opt, self.actor_opt, self.critic_opt,
         self.return_scale, metrics) = self._update(
            self.wm_params, self.actor_params, self.critic_params,
            self.slow_critic, self.wm_opt, self.actor_opt,
            self.critic_opt, self.return_scale, jbatch, key)
        return {k: float(v) for k, v in metrics.items()}

    # -- recurrent acting ----------------------------------------------
    def _policy_step_fn(self, wm, actor, h, z, prev_a, obs, first, key,
                        greedy=False):
        """One recurrent policy step for a [N]-env batch."""
        hp = self.hp
        N = obs.shape[0]
        keep = (1.0 - first)[:, None]
        h = h * keep
        z = z * keep[..., None]
        prev_a = prev_a * keep
        h = _apply_gru(wm, "gru", h,
                       jnp.concatenate([z.reshape(N, -1), prev_a], -1))
        emb = _apply_mlp(wm, "enc", symlog(obs))
        post_logits = _apply_mlp(
            wm, "post", jnp.concatenate([h, emb], -1)).reshape(
                N, hp.num_categoricals, hp.num_classes)
        kz, ka = jax.random.split(key)
        z = _sample_latent(post_logits, kz, hp)
        feat = jnp.concatenate([h, z.reshape(N, -1)], -1)
        out = _apply_mlp(actor, "actor", feat)
        if self.act_spec.kind == "discrete":
            if greedy:
                a = jnp.argmax(out, -1)
            else:
                a = jax.random.categorical(ka, out, axis=-1)
        else:
            mu, log_std = _actor_dist(out)
            if greedy:
                a = jnp.tanh(mu)
            else:
                a = jnp.tanh(mu + jnp.exp(log_std)
                             * jax.random.normal(ka, mu.shape))
        return a, h, z

    def policy_step(self, h, z, prev_a, obs, first, key, greedy=False):
        """Returns (action, h, z); continuous actions come back
        NORMALIZED to [-1, 1] (scale by act_limit before env.step)."""
        return self._policy_step(self.wm_params, self.actor_params, h, z,
                                 prev_a, obs, first, key, greedy=greedy)


# ---------------------------------------------------------------------------
# algorithm
# ---------------------------------------------------------------------------


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=DreamerV3)
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 64
        self.deter_dim = 256
        self.num_categoricals = 16
        self.num_classes = 16
        self.units = 256
        self.num_bins = 41
        self.batch_size = 16
        self.batch_length = 16
        self.horizon = 15
        self.gamma = 0.997
        self.lam = 0.95
        self.ent_coef = 3e-4
        self.lr_world = 1e-3
        self.lr_actor = 3e-4
        self.lr_critic = 3e-4
        self.num_updates_per_iteration = 8
        self.replay_capacity_per_env = 16384
        self.learning_starts = 256          # env steps before updates

    def hyperparams(self) -> DreamerV3Hyperparams:
        return DreamerV3Hyperparams(
            deter_dim=self.deter_dim,
            num_categoricals=self.num_categoricals,
            num_classes=self.num_classes, units=self.units,
            num_bins=self.num_bins, batch_size=self.batch_size,
            batch_length=self.batch_length, horizon=self.horizon,
            gamma=self.gamma, lam=self.lam, ent_coef=self.ent_coef,
            lr_world=self.lr_world, lr_actor=self.lr_actor,
            lr_critic=self.lr_critic)


class DreamerV3(Algorithm):
    """Owns a recurrent collection loop (no stateless RolloutWorker):
    posterior state is carried across env steps and reset via is_first,
    mirroring the reference's dedicated DreamerV3 EnvRunner."""

    def __init__(self, config: DreamerV3Config):
        if config.num_env_runners > 0:
            raise ValueError(
                "DreamerV3 collection is driver-local (the policy is "
                "recurrent); num_env_runners must be 0")
        if getattr(config, "num_learners", 0) > 0:
            raise ValueError(
                "DreamerV3 needs direct learner access for recurrent "
                "acting (policy_step); use "
                "resources(learner_mesh=mesh) for data-parallel SPMD "
                "updates instead of learners(num_learners=...)")
        if (config.env_to_module_connector is not None
                or config.module_to_env_connector is not None
                or config.learner_connector is not None):
            raise ValueError(
                "DreamerV3's recurrent collection loop does not run "
                "connector pipelines; configure the env itself instead")
        self.config = config
        self._iteration = 0
        self._remote = False
        self.workers: list = []
        self._eval_workers: list = []
        self.env: VectorEnv = self._make_env(
            config.num_envs_per_env_runner, config.seed)
        if self.env.continuous:
            self.act_spec = ActSpec("continuous", self.env.act_dim,
                                    float(self.env.act_limit))
        else:
            self.act_spec = ActSpec("discrete", self.env.num_actions)
        self.space_info = {"obs_dim": self.env.obs_dim,
                           "num_actions": self.env.num_actions}
        hp = config.hyperparams()
        obs_dim, act_spec = self.env.obs_dim, self.act_spec

        def factory(mesh=None):
            return DreamerV3Learner(obs_dim, act_spec, hp,
                                    seed=config.seed, mesh=mesh)

        self._made_learner_group = False
        self.learner = self._build_learner(factory)
        self.replay = SequenceReplayBuffer(config.replay_capacity_per_env,
                                           seed=config.seed)
        self._env_steps = 0
        n = self.env.num_envs
        self._obs = self.env.reset()
        self._first = np.ones(n, np.float32)
        self._prev_a = self._zero_actions(n)
        self._prev_r = np.zeros(n, np.float32)
        self._h = jnp.zeros((n, hp.deter_dim))
        self._z = jnp.zeros((n, hp.num_categoricals, hp.num_classes))
        self._rng = jax.random.PRNGKey(config.seed + 77)
        self._eval_env: Optional[VectorEnv] = None

    def _make_env(self, num_envs: int, seed: int) -> VectorEnv:
        env = self.config.env
        if callable(env):
            return env(num_envs=num_envs, seed=seed)
        return make_env(env, num_envs=num_envs, seed=seed)

    def _zero_actions(self, n: int) -> np.ndarray:
        if self.act_spec.kind == "discrete":
            return np.zeros(n, np.int64)
        return np.zeros((n, self.act_spec.n), np.float32)

    def _prev_a_input(self, prev_a: np.ndarray) -> jnp.ndarray:
        """Collection-side prev-action -> network input (normalized)."""
        if self.act_spec.kind == "discrete":
            return jax.nn.one_hot(jnp.asarray(prev_a),
                                  self.act_spec.n)
        return jnp.asarray(prev_a, jnp.float32)

    def _env_actions(self, a: np.ndarray) -> np.ndarray:
        """Network action -> env action (scale continuous to limits)."""
        if self.act_spec.kind == "discrete":
            return a
        return a * self.act_spec.limit

    def _broadcast_weights(self) -> None:
        pass  # collection reads the learner's params directly

    def _collect(self, num_steps: int) -> list:
        """Step the vec env `num_steps` times, appending on-arrival
        records; returns finished-episode returns."""
        env = self.env
        n = env.num_envs
        episode_returns = []
        for _ in range(num_steps):
            for i in range(n):
                self.replay.add(i, {
                    "obs": self._obs[i].astype(np.float32),
                    "prev_action": self._prev_a[i],
                    "reward": np.float32(self._prev_r[i]),
                    "is_first": np.float32(self._first[i]),
                    "cont": np.float32(1.0),
                })
            self._rng, key = jax.random.split(self._rng)
            a, self._h, self._z = self.learner.policy_step(
                self._h, self._z, self._prev_a_input(self._prev_a),
                jnp.asarray(self._obs, jnp.float32),
                jnp.asarray(self._first), key)
            actions = np.asarray(a)   # normalized for continuous
            obs, rewards, dones, ep_ret = env.step(
                self._env_actions(actions))
            self._env_steps += n
            for i in range(n):
                if dones[i]:
                    # terminal/truncated observation record (auto-reset
                    # envs surface it via final_obs)
                    self.replay.add(i, {
                        "obs": env.final_obs[i].astype(np.float32),
                        "prev_action": actions[i],
                        "reward": np.float32(rewards[i]),
                        "is_first": np.float32(0.0),
                        "cont": np.float32(
                            1.0 if env.truncateds[i] else 0.0),
                    })
                    self._first[i] = 1.0
                    self._prev_a[i] = 0
                    self._prev_r[i] = 0.0
                else:
                    self._first[i] = 0.0
                    self._prev_a[i] = actions[i]
                    self._prev_r[i] = rewards[i]
            self._obs = obs
            episode_returns.extend(
                float(r) for r in ep_ret[~np.isnan(ep_ret)])
        return episode_returns

    def training_step(self) -> Dict[str, float]:
        cfg: DreamerV3Config = self.config
        episode_returns = self._collect(cfg.rollout_fragment_length)
        metrics: Dict[str, float] = {}
        if (self._env_steps >= cfg.learning_starts
                and self.replay.can_sample(cfg.batch_length)):
            accum: Dict[str, list] = {}
            for _ in range(cfg.num_updates_per_iteration):
                batch = self.replay.sample(cfg.batch_size,
                                           cfg.batch_length)
                m = self.learner.update(batch)
                for k, v in m.items():
                    accum.setdefault(k, []).append(v)
            metrics.update(
                {k: float(np.mean(v)) for k, v in accum.items()})
        if episode_returns:
            metrics["episode_return_mean"] = float(
                np.mean(episode_returns))
            metrics["num_episodes"] = float(len(episode_returns))
        metrics["num_env_steps_sampled"] = float(self._env_steps)
        metrics["replay_size"] = float(len(self.replay))
        return metrics

    def evaluate(self) -> Dict[str, float]:
        """Greedy recurrent episodes on a separate env (the base
        RolloutWorker path is stateless and cannot drive this policy)."""
        cfg: DreamerV3Config = self.config
        hp = cfg.hyperparams()
        episodes = max(1, cfg.evaluation_duration)
        if self._eval_env is None:
            self._eval_env = self._make_env(1, cfg.seed + 9000)
        env = self._eval_env
        rng = jax.random.PRNGKey(cfg.seed + 4242)
        returns = []
        obs = env.reset()
        h = jnp.zeros((1, hp.deter_dim))
        z = jnp.zeros((1, hp.num_categoricals, hp.num_classes))
        prev_a = self._zero_actions(1)
        first = np.ones(1, np.float32)
        steps_cap = 2000 * episodes
        for _ in range(steps_cap):
            rng, key = jax.random.split(rng)
            a, h, z = self.learner.policy_step(
                h, z, self._prev_a_input(prev_a),
                jnp.asarray(obs, jnp.float32), jnp.asarray(first), key,
                greedy=True)
            actions = np.asarray(a)
            obs, _, dones, ep_ret = env.step(self._env_actions(actions))
            if dones[0]:
                first[0] = 1.0
                prev_a[0] = 0
                if not np.isnan(ep_ret[0]):
                    returns.append(float(ep_ret[0]))
                if len(returns) >= episodes:
                    break
            else:
                first[0] = 0.0
                prev_a[0] = actions[0]
        return {
            "evaluation/episode_return_mean": float(np.mean(returns))
            if returns else float("nan"),
            "evaluation/num_episodes": float(len(returns)),
        }
