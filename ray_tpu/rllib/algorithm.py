"""Algorithm + AlgorithmConfig: the RL training driver.

ref: rllib/algorithms/algorithm.py:196 (Algorithm, a Tune Trainable),
algorithm_config.py (builder-style config). The Algorithm owns N rollout
workers (local objects or ray_tpu actors) and one Learner; `train()` runs
one iteration and returns a metrics dict, so a function trainable can wrap
it for Tune directly (`lambda cfg: PPOConfig()...build().train()`).
"""
from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np


class AlgorithmConfig:
    def __init__(self, algo_class=None):
        self.algo_class = algo_class
        self.env: Union[str, Callable, None] = None
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 128
        self.num_cpus_per_env_runner = 1.0
        self.seed = 0
        self.model_hidden: Tuple[int, ...] = (64, 64)
        self.learner_mesh = None  # jax Mesh with a "dp" axis, or None
        self.num_learners = 0     # 0 = single inline learner
        self.remote_learners = False
        # Connector factories (ref: rllib/connectors/connector_v2.py;
        # see ray_tpu/rllib/connectors.py). env/module ones are called
        # once per rollout/eval worker; the learner connector runs
        # driver-side on every training batch before the update.
        self.env_to_module_connector = None   # () -> Connector
        self.module_to_env_connector = None   # () -> Connector
        self.learner_connector = None         # () -> Connector (batch)
        self.evaluation_interval = 0          # iterations; 0 = disabled
        self.evaluation_num_env_runners = 0   # 0 = evaluate locally
        self.evaluation_duration = 5          # episodes per evaluation

    # builder surface (each returns self, ref: algorithm_config.py)
    def environment(self, env: Union[str, Callable]) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, *, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None,
                    num_cpus_per_env_runner: Optional[float] = None,
                    env_to_module_connector: Optional[Callable] = None,
                    module_to_env_connector: Optional[Callable] = None,
                    learner_connector: Optional[Callable] = None
                    ) -> "AlgorithmConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if num_cpus_per_env_runner is not None:
            self.num_cpus_per_env_runner = num_cpus_per_env_runner
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        if learner_connector is not None:
            self.learner_connector = learner_connector
        return self

    def _worker_connectors(self) -> dict:
        """Fresh connector instances for one worker (factories may
        return a single Connector or a list to pipeline)."""
        from ray_tpu.rllib.connectors import Connector, ConnectorPipeline

        def make(factory):
            if factory is None:
                return None
            c = factory()
            if isinstance(c, (list, tuple)):
                c = ConnectorPipeline(list(c))
            if not isinstance(c, Connector):
                raise TypeError("connector factory must return a "
                                "Connector (or list of them)")
            return c

        return {"obs_connector": make(self.env_to_module_connector),
                "action_connector": make(self.module_to_env_connector)}

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def framework(self, _framework: str = "jax") -> "AlgorithmConfig":
        return self  # jax is the only framework

    def resources(self, *, learner_mesh=None, **_ignored
                  ) -> "AlgorithmConfig":
        if learner_mesh is not None:
            self.learner_mesh = learner_mesh
        return self

    def learners(self, *, num_learners: Optional[int] = None,
                 remote_learners: Optional[bool] = None
                 ) -> "AlgorithmConfig":
        """Data-parallel learner group (ref: AlgorithmConfig.learners /
        core/learner/learner_group.py:60). num_learners>0 builds a
        LearnerGroup: by default N devices of a dp mesh running the one
        fused program; remote_learners=True uses N learner actors."""
        if num_learners is not None:
            self.num_learners = num_learners
        if remote_learners is not None:
            self.remote_learners = remote_learners
        return self

    def debugging(self, *, seed: Optional[int] = None) -> "AlgorithmConfig":
        if seed is not None:
            self.seed = seed
        return self

    def evaluation(self, *, evaluation_interval: Optional[int] = None,
                   evaluation_num_env_runners: Optional[int] = None,
                   evaluation_duration: Optional[int] = None
                   ) -> "AlgorithmConfig":
        """Periodic deterministic evaluation on a SEPARATE worker set
        (ref: AlgorithmConfig.evaluation / evaluation/worker_set.py:82),
        so exploration noise never contaminates reported returns."""
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_env_runners is not None:
            self.evaluation_num_env_runners = evaluation_num_env_runners
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        return self

    def rl_module(self, *, model_hidden: Optional[Tuple[int, ...]] = None
                  ) -> "AlgorithmConfig":
        if model_hidden is not None:
            self.model_hidden = tuple(model_hidden)
        return self

    def build(self) -> "Algorithm":
        if self.algo_class is None:
            raise ValueError("AlgorithmConfig has no algo_class; use a "
                             "concrete config (e.g. PPOConfig)")
        if self.env is None:
            raise ValueError("call .environment(env) first")
        return self.algo_class(self)


class Algorithm:
    """One learner + N rollout workers; subclasses provide
    `_setup_learner` and `training_step` (ref: algorithm.py:1490)."""

    def __init__(self, config: AlgorithmConfig):
        from ray_tpu.rllib.rollout_worker import RolloutWorker

        self.config = config
        self._iteration = 0
        self._remote = config.num_env_runners > 0

        gamma = getattr(config, "gamma", 0.99)
        if self._remote:
            import ray_tpu

            if not ray_tpu.is_initialized():
                ray_tpu.init(ignore_reinit_error=True)
            cls = ray_tpu.remote(
                num_cpus=config.num_cpus_per_env_runner)(RolloutWorker)
            self.workers = [
                cls.remote(config.env,
                           num_envs=config.num_envs_per_env_runner,
                           seed=config.seed + 1000 * (i + 1),
                           bootstrap_gamma=gamma,
                           **config._worker_connectors())
                for i in range(config.num_env_runners)
            ]
            self.space_info = ray_tpu.get(
                self.workers[0].get_space_info.remote())
        else:
            self.workers = [RolloutWorker(
                config.env, num_envs=config.num_envs_per_env_runner,
                seed=config.seed, bootstrap_gamma=gamma,
                **config._worker_connectors())]
            self.space_info = self.workers[0].get_space_info()
        self._spaces = (self.space_info["obs_dim"],
                        self.space_info["num_actions"])
        self._eval_workers: List[Any] = []

        obs_dim, num_actions = self._spaces
        self._made_learner_group = False
        self.learner = self._setup_learner(obs_dim, num_actions)
        if (getattr(config, "num_learners", 0) > 0
                and not self._made_learner_group):
            raise ValueError(
                f"{type(self).__name__} has not been ported to the "
                f"Learner/LearnerGroup stack; num_learners>0 would be "
                f"silently ignored (supported: PPO, SAC, DQN, CQL, "
                f"IMPALA, APPO)")
        self._broadcast_weights()

    # -- subclass hooks -----------------------------------------------------
    def _setup_learner(self, obs_dim: int, num_actions: int):
        raise NotImplementedError

    def _build_learner(self, factory):
        """Wrap a `factory(mesh) -> Learner` into the configured learner
        topology: a LearnerGroup when num_learners>0, else one inline
        learner on config.learner_mesh. Conflicting or no-op configs
        are errors, not silent reinterpretations."""
        cfg = self.config
        if getattr(cfg, "num_learners", 0) > 0:
            if cfg.learner_mesh is not None:
                raise ValueError(
                    "learner_mesh and num_learners are mutually "
                    "exclusive: num_learners builds its own dp mesh. "
                    "Pass the mesh via resources(learner_mesh=...) "
                    "alone, or let learners(num_learners=N) claim N "
                    "devices")
            from ray_tpu.rllib.core.learner_group import LearnerGroup

            self._made_learner_group = True
            return LearnerGroup(factory,
                                num_learners=cfg.num_learners,
                                remote=cfg.remote_learners)
        if getattr(cfg, "remote_learners", False):
            raise ValueError(
                "remote_learners=True needs num_learners > 0")
        return factory(cfg.learner_mesh)

    def training_step(self) -> Dict[str, float]:
        raise NotImplementedError

    # -- shared machinery ---------------------------------------------------
    def _broadcast_weights(self) -> None:
        weights = self.learner.get_weights()
        if self._remote:
            import ray_tpu

            # put() once; workers resolve the shared ref (serialize the
            # pytree once per iteration, not once per worker).
            ref = ray_tpu.put(weights)
            ray_tpu.get([w.set_weights.remote(ref) for w in self.workers])
        else:
            self.workers[0].set_weights(weights)

    def _sample_rollouts(self) -> Tuple[Dict[str, np.ndarray], List[float]]:
        T = self.config.rollout_fragment_length
        if self._remote:
            import ray_tpu

            outs = ray_tpu.get(
                [w.sample.remote(T) for w in self.workers], timeout=600)
        else:
            outs = [self.workers[0].sample(T)]
        batch = {
            k: np.concatenate([o["batch"][k] for o in outs], axis=0)
            for k in outs[0]["batch"]
        }
        episode_returns: List[float] = []
        for o in outs:
            episode_returns.extend(o["episode_returns"])
        return self._apply_learner_connector(batch), episode_returns

    def _apply_learner_connector(self, batch):
        """Driver-side batch transform before the learner update (ref:
        the learner connector pipeline, rllib/connectors/learner/);
        built lazily from config.learner_connector."""
        factory = getattr(self.config, "learner_connector", None)
        if factory is None:
            return batch
        if not hasattr(self, "_learner_conn"):
            self._learner_conn = factory()
        return self._learner_conn(batch)

    # -- evaluation (ref: Algorithm.evaluate + worker_set.py:82) -------------
    _eval_mode = "greedy_pi"   # subclasses: greedy_q (DQN), sac_mean (SAC)

    def _ensure_eval_workers(self) -> None:
        if self._eval_workers:
            return
        from ray_tpu.rllib.rollout_worker import RolloutWorker

        cfg = self.config
        n = cfg.evaluation_num_env_runners
        gamma = getattr(cfg, "gamma", 0.99)
        if n > 0:
            import ray_tpu

            cls = ray_tpu.remote(
                num_cpus=cfg.num_cpus_per_env_runner)(RolloutWorker)
            self._eval_workers = [
                cls.remote(cfg.env, num_envs=cfg.num_envs_per_env_runner,
                           seed=cfg.seed + 9000 + i,
                           bootstrap_gamma=gamma,
                           **cfg._worker_connectors())
                for i in range(n)]
        else:
            self._eval_workers = [RolloutWorker(
                cfg.env, num_envs=cfg.num_envs_per_env_runner,
                seed=cfg.seed + 9000, bootstrap_gamma=gamma,
                **cfg._worker_connectors())]

    def _connector_state(self):
        """Training worker 0's obs-filter state (None when stateless)."""
        if getattr(self.config, "env_to_module_connector", None) is None:
            return None     # no filter: skip the remote round-trip
        m = self.workers[0].get_connector_state
        if hasattr(m, "remote"):
            import ray_tpu

            return ray_tpu.get(m.remote(), timeout=60)
        return m()

    def _push_connector_state(self, workers, state) -> None:
        if state is None or not workers:
            return
        refs = []
        for w in workers:
            m = w.set_connector_state
            if hasattr(m, "remote"):
                refs.append(m.remote(state))
            else:
                m(state)
        if refs:
            import ray_tpu

            ray_tpu.get(refs, timeout=60)

    def evaluate(self) -> Dict[str, float]:
        """Deterministic episodes on the separate eval worker set.
        Stateful obs filters sync from training worker 0 first — the
        policy must be evaluated on the observation space it was
        trained on, not a fresh count=0 filter."""
        self._ensure_eval_workers()
        cfg = self.config
        self._push_connector_state(self._eval_workers,
                                   self._connector_state())
        weights = self.learner.get_weights()
        episodes = max(1, cfg.evaluation_duration)
        if cfg.evaluation_num_env_runners > 0:
            import ray_tpu

            ref = ray_tpu.put(weights)
            ray_tpu.get([w.set_weights.remote(ref)
                         for w in self._eval_workers])
            n = len(self._eval_workers)
            per = [episodes // n + (1 if i < episodes % n else 0)
                   for i in range(n)]
            outs = ray_tpu.get(
                [w.evaluate.remote(p, mode=self._eval_mode)
                 for w, p in zip(self._eval_workers, per) if p],
                timeout=600)
            returns = [r for o in outs for r in o]
        else:
            w = self._eval_workers[0]
            w.set_weights(weights)
            returns = w.evaluate(episodes, mode=self._eval_mode)
        return {
            "evaluation/episode_return_mean": float(np.mean(returns)),
            "evaluation/num_episodes": float(len(returns)),
        }

    # -- public surface (ref: Algorithm.train/save/restore/stop) ------------
    def train(self) -> Dict[str, float]:
        self._iteration += 1
        metrics = self.training_step()
        metrics["training_iteration"] = float(self._iteration)
        interval = getattr(self.config, "evaluation_interval", 0)
        if interval and self._iteration % interval == 0:
            metrics.update(self.evaluate())
        return metrics

    def get_weights(self) -> Any:
        return self.learner.get_weights()

    def set_weights(self, weights: Any) -> None:
        self.learner.set_weights(weights)
        self._broadcast_weights()

    def save(self, checkpoint_dir: Optional[str] = None) -> str:
        checkpoint_dir = checkpoint_dir or tempfile.mkdtemp(
            prefix="rllib_ckpt_")
        os.makedirs(checkpoint_dir, exist_ok=True)
        learner_state = (self.learner.get_state()
                         if hasattr(self.learner, "get_state")
                         else {"params": self.learner.get_weights()})
        with open(os.path.join(checkpoint_dir, "algorithm.pkl"), "wb") as f:
            pickle.dump({"learner_state": learner_state,
                         "iteration": self._iteration,
                         # Stateful obs filters are part of the policy's
                         # input contract; a restore without them feeds
                         # the net a different observation scale.
                         "connector_state": self._connector_state()}, f)
        return checkpoint_dir

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm.pkl"), "rb") as f:
            state = pickle.load(f)
        self._iteration = state["iteration"]
        if hasattr(self.learner, "set_state"):
            self.learner.set_state(state["learner_state"])
        else:
            self.learner.set_weights(state["learner_state"]["params"])
        self._push_connector_state(self.workers,
                                   state.get("connector_state"))
        self._broadcast_weights()

    def stop(self) -> None:
        remote_eval = (getattr(self.config, "evaluation_num_env_runners",
                               0) > 0)
        if self._remote or remote_eval:
            import ray_tpu

            doomed = (self.workers if self._remote else []) + (
                self._eval_workers if remote_eval else [])
            for w in doomed:
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001
                    pass
        self.workers = []
        self._eval_workers = []
