"""APPO: asynchronous PPO — IMPALA's decoupled sampling/learning with
PPO's clipped surrogate objective.

ref: rllib/algorithms/appo/appo.py — the reference layers the PPO clip
(and optional KL) on top of the IMPALA architecture so stale-but-cheap
async rollouts get both V-trace off-policy correction AND the
trust-region-ish update clamp. TPU-first shape inherited from
ImpalaLearner: the entire update (v-trace scan + surrogate + optimizer)
is one jitted program; only the policy-gradient term differs.
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from ray_tpu.rllib.impala import (
    IMPALA,
    ImpalaConfig,
    ImpalaHyperparams,
    ImpalaLearner,
)


@dataclasses.dataclass(frozen=True)
class AppoHyperparams(ImpalaHyperparams):
    clip_param: float = 0.2


class AppoLearner(ImpalaLearner):
    """V-trace advantages through the PPO clipped surrogate (ref:
    appo_torch_learner.py loss; here fused into the IMPALA jit)."""

    def _pg_loss(self, target_logp, behavior_logp, pg_adv):
        eps = self.hp.clip_param
        ratio = jnp.exp(target_logp - behavior_logp)
        return -jnp.mean(jnp.minimum(
            ratio * pg_adv,
            jnp.clip(ratio, 1.0 - eps, 1.0 + eps) * pg_adv))


class APPOConfig(ImpalaConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = APPO
        self.clip_param = 0.2

    def training(self, *, clip_param=None, **kwargs) -> "APPOConfig":
        if clip_param is not None:
            self.clip_param = clip_param
        return super().training(**kwargs)

    def hyperparams(self) -> AppoHyperparams:
        base = super().hyperparams()
        return AppoHyperparams(**dataclasses.asdict(base),
                               clip_param=self.clip_param)


class APPO(IMPALA):
    """Same async training_step as IMPALA; the learner clamps updates."""

    _learner_cls = AppoLearner
