"""RLlib-equivalent: RL training on the ray_tpu runtime, JAX/TPU-first.

Reference surface (ref: rllib/algorithms/algorithm.py:196 Algorithm,
algorithm_config.py AlgorithmConfig, core/learner/learner.py:107 Learner,
evaluation/rollout_worker.py:159 RolloutWorker). Design split, TPU-style:

- **RolloutWorkers** are CPU actors stepping vectorized numpy envs with a
  jitted policy (sampling is branchy/host-bound: wrong shape for the MXU).
- **The Learner** is one jitted SPMD program: GAE, minibatch permutation,
  and all SGD epochs run inside a single `jax.jit` with `lax.scan` —
  no per-minibatch dispatch — shardable over a mesh `dp` axis with
  `NamedSharding` (the reference reaches the same goal with DDP-wrapped
  torch modules, core/learner/torch/torch_learner.py:52).
- Weight broadcast worker<-learner is a host-level actor call (DCN), the
  analogue of LearnerGroup weight sync (core/learner/learner_group.py:60).
"""
from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.connectors import (
    ActionClip,
    Connector,
    ConnectorPipeline,
    ObsClip,
    ObsNormalizer,
    RewardScale,
)
from ray_tpu.rllib.core import (
    DiscreteQModule,
    Learner,
    LearnerGroup,
    MLPPolicyModule,
    MultiRLModule,
    RLModule,
)
from ray_tpu.rllib.dqn import DQN, DQNConfig
from ray_tpu.rllib.dreamerv3 import DreamerV3, DreamerV3Config
from ray_tpu.rllib.impala import IMPALA, ImpalaConfig
from ray_tpu.rllib.env import register_env
from ray_tpu.rllib.offline import (
    BC,
    MARWIL,
    BCConfig,
    MARWILConfig,
    SampleWriter,
    read_samples,
    record_rollouts,
)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.replay_buffer import (
    PrioritizedReplayBuffer,
    ReplayBuffer,
)
from ray_tpu.rllib.cql import CQL, CQLConfig
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "ActionClip",
    "Connector",
    "ConnectorPipeline",
    "ObsClip",
    "ObsNormalizer",
    "RewardScale",
    "DiscreteQModule",
    "Learner",
    "LearnerGroup",
    "MLPPolicyModule",
    "MultiRLModule",
    "RLModule",
    "PPO",
    "PPOConfig",
    "DQN",
    "DQNConfig",
    "DreamerV3",
    "DreamerV3Config",
    "IMPALA",
    "ImpalaConfig",
    "APPO",
    "APPOConfig",
    "SAC",
    "SACConfig",
    "CQL",
    "CQLConfig",
    "BC",
    "BCConfig",
    "MARWIL",
    "MARWILConfig",
    "SampleWriter",
    "read_samples",
    "record_rollouts",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "register_env",
]

# Usage tagging (ref: usage_lib.record_library_usage; local-only,
# see ray_tpu/util/usage_stats.py)
from ray_tpu.util.usage_stats import record_library_usage as _rlu

_rlu("rllib")
del _rlu
