"""Offline RL IO + behavior cloning.

ref: rllib/offline/json_reader.py:1 (JsonReader — sharded sample files),
json_writer.py (JsonWriter — rollout recording), and the BC algorithm
(rllib/algorithms/bc). TPU-first shape: samples are columnar batches
written as parquet/JSON shards through ray_tpu.data, so offline
training rides the same distributed Dataset machinery as everything
else (shuffling, streaming, multi-reader splits), and the BC update is
one jitted negative-log-likelihood step.

    writer = SampleWriter(path)              # record during rollout
    writer.write(batch_dict); writer.close()

    ds = read_samples(path)                  # ray_tpu.data.Dataset
    bc = (BCConfig().environment("CartPole-v1")
          .offline_data(input_path=path).build())
    bc.train()                               # no env interaction at all
"""
from __future__ import annotations

import json
import os
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


class SampleWriter:
    """Shard-per-flush columnar sample recorder (ref: JsonWriter —
    max_file_size rotation; here one parquet shard per flush)."""

    def __init__(self, path: str, fmt: str = "parquet",
                 rows_per_shard: int = 10_000):
        if fmt not in ("parquet", "json"):
            raise ValueError(f"unsupported offline format {fmt!r}")
        self.path = path
        self.fmt = fmt
        self.rows_per_shard = rows_per_shard
        self._pending: List[Dict[str, np.ndarray]] = []
        self._pending_rows = 0
        os.makedirs(path, exist_ok=True)

    def write(self, batch: Dict[str, np.ndarray]) -> None:
        self._pending.append({k: np.asarray(v) for k, v in batch.items()})
        self._pending_rows += len(next(iter(batch.values())))
        if self._pending_rows >= self.rows_per_shard:
            self.flush()

    def flush(self) -> None:
        if not self._pending:
            return
        merged = {k: np.concatenate([b[k] for b in self._pending])
                  for k in self._pending[0]}
        self._pending, self._pending_rows = [], 0
        shard = os.path.join(self.path,
                             f"samples-{uuid.uuid4().hex[:12]}")
        if self.fmt == "parquet":
            import pyarrow as pa
            import pyarrow.parquet as pq

            cols = {}
            for k, v in merged.items():
                if v.ndim == 1:
                    cols[k] = pa.array(v)
                else:  # fixed-width vector columns (obs, actions)
                    cols[k] = pa.FixedSizeListArray.from_arrays(
                        pa.array(v.reshape(-1)), v.shape[1])
            pq.write_table(pa.table(cols), shard + ".parquet")
        else:
            with open(shard + ".json", "w") as f:
                for i in range(len(next(iter(merged.values())))):
                    row = {k: (v[i].tolist() if v.ndim > 1
                               else v[i].item())
                           for k, v in merged.items()}
                    f.write(json.dumps(row) + "\n")

    def close(self) -> None:
        self.flush()


def read_samples(path: str):
    """Offline shards -> ray_tpu.data Dataset (ref: JsonReader, but on
    the Dataset layer so shuffle/split/stream come for free)."""
    from ray_tpu import data as rd

    pq_files = [f for f in sorted(os.listdir(path))
                if f.endswith(".parquet")]
    if pq_files:
        return rd.read_parquet(path)
    return rd.read_json(path)


def _columnar(rows: List[dict]) -> Dict[str, np.ndarray]:
    out = {}
    for k in rows[0]:
        v0 = rows[0][k]
        if isinstance(v0, (list, np.ndarray)):
            out[k] = np.asarray([r[k] for r in rows], np.float32)
        else:
            arr = np.asarray([r[k] for r in rows])
            out[k] = arr
    return out


class BCConfig(AlgorithmConfig):
    """Behavior cloning: supervised policy learning from recorded
    samples — zero environment interaction during training."""

    def __init__(self):
        super().__init__(algo_class=BC)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.num_updates_per_iteration = 32
        self.input_path: Optional[str] = None

    def offline_data(self, *, input_path: str) -> "BCConfig":
        self.input_path = input_path
        return self

    def training(self, *, lr=None, train_batch_size=None,
                 num_updates_per_iteration=None, **kwargs) -> "BCConfig":
        for k, v in dict(
                lr=lr, train_batch_size=train_batch_size,
                num_updates_per_iteration=num_updates_per_iteration
        ).items():
            if v is not None:
                setattr(self, k, v)
        return super().training(**kwargs)


class BCLearner:
    """Jitted NLL step over the discrete policy head."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 seed: int = 0, hidden=(64, 64)):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models import apply_mlp_policy, init_mlp_policy

        rng = jax.random.PRNGKey(seed)
        self.params = init_mlp_policy(rng, obs_dim, num_actions, hidden)
        self._tx = optax.adam(lr)
        self.opt_state = self._tx.init(self.params)

        def loss_fn(params, obs, actions):
            logits, _ = apply_mlp_policy(params, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, actions[:, None],
                                       axis=1)[:, 0]
            return nll.mean()

        def update(params, opt_state, obs, actions):
            loss, grads = jax.value_and_grad(loss_fn)(params, obs, actions)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update, donate_argnums=(0, 1))

    def update(self, obs: np.ndarray, actions: np.ndarray) -> float:
        import jax.numpy as jnp

        self.params, self.opt_state, loss = self._update(
            self.params, self.opt_state, jnp.asarray(obs),
            jnp.asarray(actions.astype(np.int32)))
        return float(loss)

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        import jax

        self.params = jax.device_put(params)


class BC(Algorithm):
    """training_step: sample minibatches from the OFFLINE dataset (no
    env rollouts); the env is only used for spaces and evaluation."""

    def _setup_learner(self, obs_dim: int, num_actions: int) -> BCLearner:
        cfg: BCConfig = self.config
        if not cfg.input_path:
            raise ValueError("BCConfig.offline_data(input_path=...) first")
        ds = read_samples(cfg.input_path)
        rows = ds.take_all()
        data = _columnar(rows)
        self._obs = data["obs"].astype(np.float32)
        self._actions = data["actions"].astype(np.int64)
        self._rng = np.random.default_rng(cfg.seed)
        return BCLearner(obs_dim, num_actions, cfg.lr, seed=cfg.seed,
                         hidden=cfg.model_hidden)

    def training_step(self) -> Dict[str, float]:
        cfg: BCConfig = self.config
        losses = []
        n = len(self._obs)
        for _ in range(cfg.num_updates_per_iteration):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            losses.append(self.learner.update(self._obs[idx],
                                              self._actions[idx]))
        self._broadcast_weights()
        return {"bc_loss": float(np.mean(losses)),
                "num_offline_rows": float(n)}


def discounted_returns(rewards: np.ndarray, dones: np.ndarray,
                       gamma: float) -> np.ndarray:
    """Per-row Monte-Carlo returns over recorded episodes (trailing
    partial episodes bootstrap 0 — offline data has no value net yet)."""
    out = np.zeros_like(rewards, dtype=np.float32)
    acc = 0.0
    for i in range(len(rewards) - 1, -1, -1):
        if dones[i]:
            acc = 0.0
        acc = rewards[i] + gamma * acc
        out[i] = acc
    return out


class MARWILConfig(BCConfig):
    """Monotonic Advantage Re-Weighted Imitation Learning (ref:
    rllib/algorithms/marwil/marwil.py): behavior cloning where each
    action's log-likelihood is weighted by exp(beta * advantage), so
    good recorded behavior is imitated harder than bad. beta=0 reduces
    exactly to BC (the reference documents the same identity)."""

    def __init__(self):
        super().__init__()
        self.algo_class = MARWIL
        self.beta = 1.0
        self.gamma = 0.99
        self.vf_coeff = 1.0

    def training(self, *, beta=None, gamma=None, vf_coeff=None,
                 **kwargs) -> "MARWILConfig":
        for k, v in dict(beta=beta, gamma=gamma,
                         vf_coeff=vf_coeff).items():
            if v is not None:
                setattr(self, k, v)
        return super().training(**kwargs)


class MARWILLearner:
    """ONE jitted update: value regression to Monte-Carlo returns +
    advantage-exponentiated NLL through the shared pi/v towers
    (the reference runs separate torch losses; fused here)."""

    def __init__(self, obs_dim: int, num_actions: int, lr: float,
                 beta: float, vf_coeff: float, seed: int = 0,
                 hidden=(64, 64)):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.rllib.models import apply_mlp_policy, init_mlp_policy

        rng = jax.random.PRNGKey(seed)
        self.params = init_mlp_policy(rng, obs_dim, num_actions, hidden)
        self._tx = optax.adam(lr)
        self.opt_state = self._tx.init(self.params)

        def loss_fn(params, obs, actions, returns):
            logits, value = apply_mlp_policy(params, obs)
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, actions[:, None],
                                       axis=1)[:, 0]
            vf = jnp.square(value - returns)
            adv = jax.lax.stop_gradient(returns - value)
            # Batch-normalized advantage inside the exp keeps the
            # weights scale-free (the reference tracks a running moment
            # for the same purpose, marwil.py moving-average c^2).
            a_norm = adv / (jnp.std(adv) + 1e-6)
            w = jnp.minimum(jnp.exp(beta * a_norm), 20.0)  # clip blowup
            return (w * nll).mean() + vf_coeff * vf.mean(), (
                nll.mean(), vf.mean())

        def update(params, opt_state, obs, actions, returns):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, obs, actions, returns)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state,
                    loss, aux)

        self._update = jax.jit(update, donate_argnums=(0, 1))

    def update(self, obs, actions, returns) -> Dict[str, float]:
        import jax.numpy as jnp

        self.params, self.opt_state, loss, (nll, vf) = self._update(
            self.params, self.opt_state, jnp.asarray(obs),
            jnp.asarray(actions.astype(np.int32)), jnp.asarray(returns))
        return {"marwil_loss": float(loss), "policy_nll": float(nll),
                "vf_loss": float(vf)}

    def get_weights(self):
        import jax

        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        import jax

        self.params = jax.device_put(params)


class MARWIL(Algorithm):
    """Offline training_step like BC, but with per-row Monte-Carlo
    returns feeding the advantage weights."""

    def _setup_learner(self, obs_dim: int, num_actions: int
                       ) -> MARWILLearner:
        cfg: MARWILConfig = self.config
        if not cfg.input_path:
            raise ValueError(
                "MARWILConfig.offline_data(input_path=...) first")
        ds = read_samples(cfg.input_path)
        data = _columnar(ds.take_all())
        self._obs = data["obs"].astype(np.float32)
        self._actions = data["actions"].astype(np.int64)
        self._returns = discounted_returns(
            data["rewards"].astype(np.float32),
            data["dones"].astype(bool), cfg.gamma)
        self._rng = np.random.default_rng(cfg.seed)
        return MARWILLearner(obs_dim, num_actions, cfg.lr,
                             beta=cfg.beta, vf_coeff=cfg.vf_coeff,
                             seed=cfg.seed, hidden=cfg.model_hidden)

    def training_step(self) -> Dict[str, float]:
        cfg: MARWILConfig = self.config
        agg: Dict[str, list] = {}
        n = len(self._obs)
        for _ in range(cfg.num_updates_per_iteration):
            idx = self._rng.integers(0, n, cfg.train_batch_size)
            m = self.learner.update(self._obs[idx], self._actions[idx],
                                    self._returns[idx])
            for k, v in m.items():
                agg.setdefault(k, []).append(v)
        self._broadcast_weights()
        out = {k: float(np.mean(v)) for k, v in agg.items()}
        out["num_offline_rows"] = float(n)
        return out


def record_rollouts(algo: Algorithm, path: str, num_iterations: int = 4,
                    fmt: str = "parquet") -> str:
    """Record an algorithm's on-policy rollouts to offline shards
    (ref: `output` config in the reference — rollout recording)."""
    writer = SampleWriter(path, fmt=fmt)
    for _ in range(num_iterations):
        batch, _ = algo._sample_rollouts()
        flat = {
            "obs": batch["obs"].reshape(-1, batch["obs"].shape[-1]),
            "actions": batch["actions"].reshape(-1),
            "rewards": batch["rewards"].reshape(-1),
            "dones": batch["dones"].reshape(-1),
        }
        writer.write(flat)
    writer.close()
    return path
