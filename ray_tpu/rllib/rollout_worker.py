"""RolloutWorker: CPU-side experience collection with a jitted policy.

ref: rllib/evaluation/rollout_worker.py:159. Runs as a plain object
(local mode) or a ray_tpu actor; steps a numpy VectorEnv in lockstep and
batches every policy forward through one jitted call — sampling stays on
CPU where the branchy env code lives, the learner stays on the mesh.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.env import VectorEnv, make_env
from ray_tpu.rllib.models import apply_mlp_policy


@jax.jit
def _policy_step(params, obs, key):
    logits, value = apply_mlp_policy(params, obs)
    actions = jax.random.categorical(key, logits)
    logp_all = jax.nn.log_softmax(logits)
    logp = jnp.take_along_axis(logp_all, actions[:, None], axis=1)[:, 0]
    return actions, logp, value


@jax.jit
def _value_only(params, obs):
    return apply_mlp_policy(params, obs)[1]


@jax.jit
def _policy_logits(params, obs):
    return apply_mlp_policy(params, obs)


@jax.jit
def _sac_policy_step(params, obs, key, act_limit):
    """Stochastic squashed-Gaussian sample for off-policy collection."""
    from ray_tpu.rllib.models import apply_sac_actor, sample_squashed

    mu, log_std = apply_sac_actor(params, obs)
    a, _ = sample_squashed(mu, log_std, key, act_limit)
    return a


@jax.jit
def _sac_mean_action(params, obs, act_limit):
    from ray_tpu.rllib.models import apply_sac_actor

    mu, _ = apply_sac_actor(params, obs)
    return jnp.tanh(mu) * act_limit


@jax.jit
def _q_policy_step(params, obs, key, epsilon):
    """Epsilon-greedy over Q(s, .) for off-policy collection."""
    from ray_tpu.rllib.models import apply_mlp_q

    q = apply_mlp_q(params, obs)
    greedy = jnp.argmax(q, axis=1)
    k1, k2 = jax.random.split(key)
    rand_a = jax.random.randint(k1, greedy.shape, 0, q.shape[1])
    explore = jax.random.uniform(k2, greedy.shape) < epsilon
    return jnp.where(explore, rand_a, greedy)


class RolloutWorker:
    def __init__(self, env: Union[str, Callable[..., VectorEnv]],
                 num_envs: int = 8, seed: int = 0,
                 bootstrap_gamma: float = 0.99,
                 obs_connector=None, action_connector=None):
        if callable(env):
            self.env = env(num_envs=num_envs, seed=seed)
        else:
            self.env = make_env(env, num_envs=num_envs, seed=seed)
        self.obs_dim = self.env.obs_dim
        self.num_actions = self.env.num_actions
        # env->module / module->env connector pipelines (ref:
        # rllib/connectors/connector_v2.py; see rllib/connectors.py).
        # The module only ever sees FILTERED observations — including
        # bootstrap-value calls on final_obs — so train and act spaces
        # stay consistent.
        self._obs_connector = obs_connector
        self._action_connector = action_connector
        self._obs = self._filter(self.env.reset())
        self._params = None
        self._rng = jax.random.PRNGKey(seed + 1)
        # Time-limit cuts bootstrap the truncated state's value into the
        # reward (done=1 with no bootstrap would bias V targets low).
        self._gamma = bootstrap_gamma

    def _filter(self, obs: np.ndarray) -> np.ndarray:
        return obs if self._obs_connector is None else \
            self._obs_connector(obs)

    def _act(self, actions: np.ndarray) -> np.ndarray:
        return actions if self._action_connector is None else \
            self._action_connector(actions)

    def get_connector_state(self):
        return (self._obs_connector.get_state()
                if self._obs_connector is not None else None)

    def set_connector_state(self, state) -> None:
        """Restore the obs filter (checkpoint restore / eval sync) —
        the policy was trained on THIS filter's output space."""
        if self._obs_connector is not None and state is not None:
            self._obs_connector.set_state(state)

    def get_space_info(self) -> Dict[str, Any]:
        return {
            "obs_dim": self.obs_dim,
            "num_actions": self.num_actions,
            "continuous": getattr(self.env, "continuous", False),
            "act_dim": getattr(self.env, "act_dim", 0),
            "act_limit": getattr(self.env, "act_limit", 1.0),
        }

    def set_weights(self, params: Any) -> None:
        self._params = jax.device_put(params)

    def sample(self, num_steps: int) -> Dict[str, Any]:
        """Collect `num_steps` per env; returns batch arrays [E, T, ...] +
        the bootstrap value and finished-episode returns."""
        assert self._params is not None, "set_weights() before sample()"
        E = self.env.num_envs
        obs_buf = np.empty((E, num_steps, self.obs_dim), np.float32)
        act_buf = np.empty((E, num_steps), np.int32)
        logp_buf = np.empty((E, num_steps), np.float32)
        rew_buf = np.empty((E, num_steps), np.float32)
        done_buf = np.empty((E, num_steps), np.float32)
        val_buf = np.empty((E, num_steps), np.float32)
        episode_returns: List[float] = []

        obs = self._obs
        for t in range(num_steps):
            self._rng, key = jax.random.split(self._rng)
            actions, logp, value = _policy_step(self._params, obs, key)
            actions = np.asarray(actions)
            obs_buf[:, t] = obs
            act_buf[:, t] = actions
            logp_buf[:, t] = np.asarray(logp)
            val_buf[:, t] = np.asarray(value)
            obs, rewards, dones, ep_ret = self.env.step(
                self._act(actions))
            obs = self._filter(obs)
            trunc = getattr(self.env, "truncateds", None)
            if trunc is not None and trunc.any():
                # Full-batch value call keeps the jit shape static.
                vals = np.asarray(_value_only(
                    self._params, self._filter(self.env.final_obs)),
                    np.float32)
                rewards = rewards.copy()
                rewards[trunc] += self._gamma * vals[trunc]
            rew_buf[:, t] = rewards
            done_buf[:, t] = dones
            finished = ~np.isnan(ep_ret)
            if finished.any():
                episode_returns.extend(ep_ret[finished].tolist())
        self._obs = obs
        final_value = np.asarray(_value_only(self._params, obs), np.float32)
        return {
            "batch": {
                "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
                "rewards": rew_buf, "dones": done_buf, "values": val_buf,
                "final_value": final_value,
            },
            "episode_returns": episode_returns,
        }

    def sample_transitions_continuous(self, num_steps: int,
                                      uniform: bool = False
                                      ) -> Dict[str, Any]:
        """Off-policy continuous collection (SAC): float actions from the
        squashed-Gaussian actor (or uniform random warmup), transitions
        with truncation-aware terminals like sample_transitions."""
        E = self.env.num_envs
        act_dim = self.env.act_dim
        limit = float(self.env.act_limit)
        obs_buf = np.empty((E * num_steps, self.obs_dim), np.float32)
        act_buf = np.empty((E * num_steps, act_dim), np.float32)
        rew_buf = np.empty((E * num_steps,), np.float32)
        next_buf = np.empty((E * num_steps, self.obs_dim), np.float32)
        term_buf = np.empty((E * num_steps,), np.float32)
        episode_returns: List[float] = []

        obs = self._obs
        for t in range(num_steps):
            self._rng, key = jax.random.split(self._rng)
            if uniform:
                actions = np.asarray(jax.random.uniform(
                    key, (E, act_dim), minval=-limit, maxval=limit))
            else:
                assert self._params is not None
                actions = np.asarray(_sac_policy_step(
                    self._params, obs, key, limit))
            lo, hi = t * E, (t + 1) * E
            obs_buf[lo:hi] = obs
            act_buf[lo:hi] = actions
            obs, rewards, dones, ep_ret = self.env.step(
                self._act(actions))
            obs = self._filter(obs)
            rew_buf[lo:hi] = rewards
            next_buf[lo:hi] = self._filter(self.env.final_obs)
            trunc = getattr(self.env, "truncateds", None)
            terminal = dones.astype(np.float32)
            if trunc is not None:
                terminal = terminal * (1.0 - trunc.astype(np.float32))
            term_buf[lo:hi] = terminal
            finished = ~np.isnan(ep_ret)
            if finished.any():
                episode_returns.extend(ep_ret[finished].tolist())
        self._obs = obs
        return {
            "batch": {
                "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
                "next_obs": next_buf, "terminals": term_buf,
            },
            "episode_returns": episode_returns,
        }

    def evaluate(self, num_episodes: int, mode: str = "greedy_pi"
                 ) -> List[float]:
        """Deterministic evaluation episodes on FRESH env state (ref:
        evaluation workers, rllib/evaluation/worker_set.py:82 — separate
        from training collection so metrics aren't exploration-noised).
        mode: greedy_pi (argmax logits) | greedy_q (argmax Q) |
        sac_mean (tanh(mu))."""
        assert self._params is not None, "set_weights() before evaluate()"
        limit = float(getattr(self.env, "act_limit", 1.0))
        returns: List[float] = []
        obs = self._filter(self.env.reset())
        guard = 0
        while len(returns) < num_episodes and guard < 100_000:
            guard += 1
            if mode == "sac_mean":
                actions = np.asarray(_sac_mean_action(self._params, obs,
                                                      limit))
            elif mode == "greedy_q":
                from ray_tpu.rllib.models import apply_mlp_q

                actions = np.asarray(jnp.argmax(
                    apply_mlp_q(self._params, jnp.asarray(obs)), axis=1))
            else:
                logits, _ = _policy_logits(self._params, obs)
                actions = np.asarray(jnp.argmax(logits, axis=1))
            obs, _, _, ep_ret = self.env.step(self._act(actions))
            obs = self._filter(obs)
            done = ~np.isnan(ep_ret)
            if done.any():
                returns.extend(ep_ret[done].tolist())
        self._obs = self._filter(self.env.reset())  # training state fresh
        return returns[:num_episodes]

    def sample_transitions(self, num_steps: int,
                           epsilon: float = 0.0) -> Dict[str, Any]:
        """Off-policy collection for DQN-style learners: flat
        (s, a, r, s', terminal) transitions with epsilon-greedy actions.
        `terminal` excludes time-limit truncations (those bootstrap), and
        s' is the PRE-reset observation on episode ends (the auto-reset
        obs would poison TD targets)."""
        assert self._params is not None, "set_weights() before sample()"
        E = self.env.num_envs
        obs_buf = np.empty((E * num_steps, self.obs_dim), np.float32)
        act_buf = np.empty((E * num_steps,), np.int32)
        rew_buf = np.empty((E * num_steps,), np.float32)
        next_buf = np.empty((E * num_steps, self.obs_dim), np.float32)
        term_buf = np.empty((E * num_steps,), np.float32)
        episode_returns: List[float] = []

        obs = self._obs
        eps = jnp.float32(epsilon)
        for t in range(num_steps):
            self._rng, key = jax.random.split(self._rng)
            actions = np.asarray(_q_policy_step(self._params, obs, key,
                                                eps))
            lo, hi = t * E, (t + 1) * E
            obs_buf[lo:hi] = obs
            act_buf[lo:hi] = actions
            obs, rewards, dones, ep_ret = self.env.step(
                self._act(actions))
            obs = self._filter(obs)
            # final_obs is every env's TRUE successor state this step.
            rew_buf[lo:hi] = rewards
            next_buf[lo:hi] = self._filter(self.env.final_obs)
            trunc = getattr(self.env, "truncateds", None)
            terminal = dones.astype(np.float32)
            if trunc is not None:
                terminal = terminal * (1.0 - trunc.astype(np.float32))
            term_buf[lo:hi] = terminal
            finished = ~np.isnan(ep_ret)
            if finished.any():
                episode_returns.extend(ep_ret[finished].tolist())
        self._obs = obs
        return {
            "batch": {
                "obs": obs_buf, "actions": act_buf, "rewards": rew_buf,
                "next_obs": next_buf, "terminals": term_buf,
            },
            "episode_returns": episode_returns,
        }
