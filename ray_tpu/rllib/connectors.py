"""Connectors: composable transforms between env, module, and learner.

ref: rllib/connectors/connector_v2.py — the new-stack pipeline that
sits on the three seams (env→module for observations, module→env for
actions, learner for training batches) so preprocessing lives OUTSIDE
both the environment and the network.

TPU-first shape: connectors are plain numpy/host-side transforms —
they run inside CPU rollout actors where branchy per-step work belongs,
keeping the jitted policy/learner programs free of data-dependent
preprocessing. Stateful connectors (running normalization) expose
get_state/set_state so checkpoints capture them.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class Connector:
    """One transform; __call__ must be shape-preserving or document
    its output space (obs_dim changes are not supported yet)."""

    def __call__(self, data: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConnectorPipeline(Connector):
    """Ordered composition (ref: connector_pipeline_v2.py)."""

    def __init__(self, connectors: List[Connector]):
        self.connectors = list(connectors)

    def __call__(self, data):
        for c in self.connectors:
            data = c(data)
        return data

    def get_state(self):
        return {str(i): c.get_state()
                for i, c in enumerate(self.connectors)}

    def set_state(self, state):
        for i, c in enumerate(self.connectors):
            if str(i) in state:
                c.set_state(state[str(i)])


class ObsNormalizer(Connector):
    """Running mean/std observation filter (ref: the MeanStdFilter
    connector role): Welford accumulation over every observation seen,
    normalize to ~N(0,1), clip outliers. Each rollout worker keeps its
    own stream — the filter converges to the same statistics on every
    worker since they sample the same policy/env distribution."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self.count = 0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None

    def __call__(self, obs: np.ndarray) -> np.ndarray:
        obs = np.asarray(obs, np.float32)
        batch = obs.reshape(-1, obs.shape[-1]).astype(np.float64)
        if self.mean is None:
            self.mean = np.zeros(batch.shape[-1], np.float64)
            self.m2 = np.zeros(batch.shape[-1], np.float64)
        # Batched Chan parallel-variance merge: ONE vectorized update
        # per call (this sits on the hot rollout path, up to 3x per
        # env step — a per-row Python Welford loop costs O(E)
        # interpreter iterations per step).
        b_count = len(batch)
        if b_count:
            b_mean = batch.mean(axis=0)
            b_m2 = ((batch - b_mean) ** 2).sum(axis=0)
            total = self.count + b_count
            delta = b_mean - self.mean
            self.m2 += b_m2 + delta ** 2 * (self.count * b_count / total)
            self.mean += delta * (b_count / total)
            self.count = total
        std = np.sqrt(self.m2 / max(1, self.count - 1)) + self.eps
        out = (obs - self.mean.astype(np.float32)) / std.astype(np.float32)
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def get_state(self):
        return {"count": self.count,
                "mean": None if self.mean is None else self.mean.copy(),
                "m2": None if self.m2 is None else self.m2.copy()}

    def set_state(self, state):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


class ObsClip(Connector):
    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, obs):
        return np.clip(obs, self.low, self.high)


class ActionClip(Connector):
    """module→env: bound continuous actions to the env's legal range."""

    def __init__(self, low: float, high: float):
        self.low, self.high = low, high

    def __call__(self, actions):
        return np.clip(actions, self.low, self.high)


class RewardScale(Connector):
    """learner connector (wire via
    `.env_runners(learner_connector=lambda: RewardScale(s))`): scales
    rewards in the training batch — a dict transform operating on the
    'rewards' key, leaving the rest untouched."""

    def __init__(self, scale: float):
        self.scale = scale

    def __call__(self, batch):
        out = dict(batch)
        out["rewards"] = np.asarray(batch["rewards"]) * self.scale
        return out
