"""Replay buffers: uniform + prioritized experience replay.

ref: rllib/utils/replay_buffers/{replay_buffer.py,
prioritized_replay_buffer.py} — ring storage, proportional priority
sampling with importance weights and post-update priority writes.
Storage is flat numpy rings (one array per field), so sampling is pure
vectorized indexing.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._size = 0
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.empty((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()}
        idx = (self._pos + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = v
        self._pos = (self._pos + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        self._on_add(idx)

    def _on_add(self, idx: np.ndarray) -> None:
        pass

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        out = {k: v[idx] for k, v in self._store.items()}
        out["batch_indexes"] = idx
        out["weights"] = np.ones(batch_size, np.float32)
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        pass  # uniform: no-op


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER (ref: prioritized_replay_buffer.py): sample
    P(i) ∝ p_i^alpha, correct with importance weights
    w_i = (N * P(i))^-beta / max w, write back |td_error| + eps."""

    def __init__(self, capacity: int, *, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._prio = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def _on_add(self, idx: np.ndarray) -> None:
        self._prio[idx] = self._max_prio ** self.alpha

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        p = self._prio[:self._size]
        total = p.sum()
        if total <= 0:
            return super().sample(batch_size)
        probs = p / total
        idx = self._rng.choice(self._size, batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights = (weights / weights.max()).astype(np.float32)
        out = {k: v[idx] for k, v in self._store.items()}
        out["batch_indexes"] = idx
        out["weights"] = weights
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        pr = np.abs(priorities) + self.eps
        self._prio[idx] = pr ** self.alpha
        self._max_prio = max(self._max_prio, float(pr.max()))
