"""Replay buffers: uniform + prioritized experience replay.

ref: rllib/utils/replay_buffers/{replay_buffer.py,
prioritized_replay_buffer.py} — ring storage, proportional priority
sampling with importance weights and post-update priority writes.
Storage is flat numpy rings (one array per field), so sampling is pure
vectorized indexing.
"""
from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._size = 0
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, batch: Dict[str, np.ndarray]) -> None:
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.empty((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()}
        idx = (self._pos + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = v
        self._pos = (self._pos + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        self._on_add(idx)

    def _on_add(self, idx: np.ndarray) -> None:
        pass

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        out = {k: v[idx] for k, v in self._store.items()}
        out["batch_indexes"] = idx
        out["weights"] = np.ones(batch_size, np.float32)
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        pass  # uniform: no-op


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER (ref: prioritized_replay_buffer.py): sample
    P(i) ∝ p_i^alpha, correct with importance weights
    w_i = (N * P(i))^-beta / max w, write back |td_error| + eps."""

    def __init__(self, capacity: int, *, alpha: float = 0.6,
                 beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self.eps = eps
        self._prio = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def _on_add(self, idx: np.ndarray) -> None:
        self._prio[idx] = self._max_prio ** self.alpha

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        p = self._prio[:self._size]
        total = p.sum()
        if total <= 0:
            return super().sample(batch_size)
        probs = p / total
        idx = self._rng.choice(self._size, batch_size, p=probs)
        weights = (self._size * probs[idx]) ** (-self.beta)
        weights = (weights / weights.max()).astype(np.float32)
        out = {k: v[idx] for k, v in self._store.items()}
        out["batch_indexes"] = idx
        out["weights"] = weights
        return out

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        pr = np.abs(priorities) + self.eps
        self._prio[idx] = pr ** self.alpha
        self._max_prio = max(self._max_prio, float(pr.max()))


class SequenceReplayBuffer:
    """Contiguous-window replay for recurrent world models.

    ref: rllib/utils/replay_buffers/episode_replay_buffer.py — the
    reference stores episodes and samples fixed-length chunks for
    DreamerV3. Here each env stream gets its own time-ring of numpy
    arrays; `sample(B, L)` returns [B, L, ...] windows drawn uniformly
    over (env, start) pairs. Windows never cross the ring's write head
    (they may span episode boundaries — records carry `is_first` so the
    model resets its recurrent state mid-window, exactly how the
    reference feeds chunked sequences).
    """

    def __init__(self, capacity_per_env: int, seed: int = 0):
        self.capacity = capacity_per_env
        self._streams: list = []           # env -> field -> [cap, ...]
        self._len: list = []               # env -> valid records
        self._pos: list = []               # env -> next write slot
        self._rng = np.random.default_rng(seed)
        self._total = 0

    def __len__(self) -> int:
        return self._total

    def add(self, env_i: int, record: Dict[str, np.ndarray]) -> None:
        """Append one record (field -> scalar or 1-D array) to env_i's
        stream."""
        while len(self._streams) <= env_i:
            self._streams.append(None)
            self._len.append(0)
            self._pos.append(0)
        if self._streams[env_i] is None:
            self._streams[env_i] = {
                k: np.zeros((self.capacity,) + np.shape(v),
                            np.asarray(v).dtype)
                for k, v in record.items()}
        st = self._streams[env_i]
        pos = self._pos[env_i]
        for k, v in record.items():
            st[k][pos] = v
        self._pos[env_i] = (pos + 1) % self.capacity
        if self._len[env_i] < self.capacity:
            self._len[env_i] += 1
            self._total += 1

    def can_sample(self, length: int) -> bool:
        return any(n >= length for n in self._len)

    def sample(self, batch_size: int, length: int
               ) -> Dict[str, np.ndarray]:
        """[B, L, ...] windows, uniform over (env, start) pairs: each
        env is weighted by its valid-window count, so records in short
        streams are not oversampled. Envs with fewer than `length`
        records are excluded; raises if no env has enough yet."""
        ok = [i for i, n in enumerate(self._len) if n >= length]
        if not ok:
            raise ValueError(
                f"no env stream has {length} records yet (sizes: "
                f"{self._len})")
        windows = np.array([self._len[i] - length + 1 for i in ok],
                           np.float64)
        envs = self._rng.choice(ok, batch_size, p=windows / windows.sum())
        batches = {k: [] for k in self._streams[ok[0]]}
        for i in envs:
            n, pos = self._len[i], self._pos[i]
            start = int(self._rng.integers(0, n - length + 1))
            # oldest record lives at (pos - n) mod cap
            idx = (pos - n + start + np.arange(length)) % self.capacity
            for k, arr in self._streams[i].items():
                batches[k].append(arr[idx])
        return {k: np.stack(v) for k, v in batches.items()}
