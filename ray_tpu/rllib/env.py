"""Environments: a numpy-vectorized env API + registry.

The reference wraps gym/gymnasium envs per rollout worker
(ref: rllib/env/, evaluation/rollout_worker.py:159). Here the native env
interface is *vectorized from the start* (one `VectorEnv` per worker
stepping `num_envs` in lockstep numpy ops) because the policy forward is a
jitted batch call — per-env Python stepping would starve it. Gymnasium
envs are adapted when the package is present; CartPole ships built-in so
the RL stack has zero hard deps.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import numpy as np

_ENV_REGISTRY: Dict[str, Callable[..., "VectorEnv"]] = {}


def register_env(name: str, creator: Callable[..., "VectorEnv"]) -> None:
    """ref: ray.tune.registry.register_env — creator(num_envs, seed)."""
    _ENV_REGISTRY[name] = creator


def make_env(name: str, num_envs: int, seed: int = 0) -> "VectorEnv":
    if name in _ENV_REGISTRY:
        return _ENV_REGISTRY[name](num_envs=num_envs, seed=seed)
    if name in ("CartPole-v1", "CartPole"):
        return CartPoleVecEnv(num_envs=num_envs, seed=seed)
    if name in ("Pendulum-v1", "Pendulum"):
        return PendulumVecEnv(num_envs=num_envs, seed=seed)
    try:
        return GymnasiumVecEnv(name, num_envs=num_envs, seed=seed)
    except ImportError:
        raise ValueError(
            f"unknown env {name!r}: not registered, not built-in, and "
            f"gymnasium is unavailable") from None


class VectorEnv:
    """Batch of envs stepped in lockstep; auto-resets finished episodes.

    Discrete envs set `num_actions`; continuous-control envs set
    `continuous=True` with `act_dim`/`act_limit` (actions are float
    arrays in [-act_limit, act_limit]^act_dim)."""

    num_envs: int
    obs_dim: int
    num_actions: int = 0
    continuous: bool = False
    act_dim: int = 0
    act_limit: float = 1.0

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, actions: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Returns (obs, rewards, dones, episode_returns) where
        episode_returns[i] is NaN except on the step env i finished.

        After each step, `self.truncateds` marks envs cut by a time limit
        (done but NOT terminal — the learner must bootstrap their value)
        and `self.final_obs` holds every env's pre-reset observation, so
        a truncated state's value is still computable."""
        raise NotImplementedError

    truncateds: np.ndarray
    final_obs: np.ndarray


class CartPoleVecEnv(VectorEnv):
    """Vectorized CartPole (classic Barto-Sutton-Anderson dynamics, the
    same physics constants gymnasium's CartPole-v1 documents)."""

    GRAVITY = 9.8
    CART_MASS = 1.0
    POLE_MASS = 0.1
    POLE_HALF_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * np.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    num_actions = 2
    obs_dim = 4

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros((num_envs, 4), dtype=np.float64)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        self._returns = np.zeros(num_envs, dtype=np.float64)

    def _reset_idx(self, idx: np.ndarray) -> None:
        self._state[idx] = self._rng.uniform(-0.05, 0.05, (idx.sum(), 4))
        self._steps[idx] = 0
        self._returns[idx] = 0.0

    def reset(self) -> np.ndarray:
        all_idx = np.ones(self.num_envs, dtype=bool)
        self._reset_idx(all_idx)
        self.truncateds = np.zeros(self.num_envs, dtype=bool)
        self.final_obs = self._state.astype(np.float32)
        return self._state.astype(np.float32)

    def step(self, actions: np.ndarray):
        x, x_dot, theta, theta_dot = self._state.T
        force = np.where(actions == 1, self.FORCE_MAG, -self.FORCE_MAG)
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        total_mass = self.CART_MASS + self.POLE_MASS
        pole_ml = self.POLE_MASS * self.POLE_HALF_LEN
        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.POLE_HALF_LEN
            * (4.0 / 3.0 - self.POLE_MASS * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass
        x = x + self.TAU * x_dot
        x_dot = x_dot + self.TAU * x_acc
        theta = theta + self.TAU * theta_dot
        theta_dot = theta_dot + self.TAU * theta_acc
        self._state = np.stack([x, x_dot, theta, theta_dot], axis=1)
        self._steps += 1
        self._returns += 1.0

        failed = ((np.abs(x) > self.X_LIMIT)
                  | (np.abs(theta) > self.THETA_LIMIT))
        truncated = (self._steps >= self.MAX_STEPS) & ~failed
        dones = failed | truncated
        rewards = np.ones(self.num_envs, dtype=np.float32)
        self.truncateds = truncated.copy()
        self.final_obs = self._state.astype(np.float32)

        episode_returns = np.full(self.num_envs, np.nan)
        if dones.any():
            episode_returns[dones] = self._returns[dones]
            self._reset_idx(dones)
        return (self._state.astype(np.float32), rewards,
                dones.astype(np.float32), episode_returns)


class PendulumVecEnv(VectorEnv):
    """Vectorized Pendulum swing-up (the classic continuous-control
    benchmark; same dynamics constants gymnasium's Pendulum-v1
    documents): obs [cosθ, sinθ, θ̇], one torque action in [-2, 2],
    reward -(θ² + 0.1 θ̇² + 0.001 a²), 200-step time limit (always a
    truncation — there is no terminal state)."""

    MAX_SPEED = 8.0
    MAX_TORQUE = 2.0
    DT = 0.05
    G = 10.0
    M = 1.0
    L = 1.0
    MAX_STEPS = 200

    obs_dim = 3
    continuous = True
    act_dim = 1
    act_limit = 2.0

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self._theta = np.zeros(num_envs)
        self._theta_dot = np.zeros(num_envs)
        self._steps = np.zeros(num_envs, dtype=np.int64)
        self._returns = np.zeros(num_envs)

    def _reset_idx(self, idx: np.ndarray) -> None:
        n = int(idx.sum())
        self._theta[idx] = self._rng.uniform(-np.pi, np.pi, n)
        self._theta_dot[idx] = self._rng.uniform(-1.0, 1.0, n)
        self._steps[idx] = 0
        self._returns[idx] = 0.0

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self._theta), np.sin(self._theta),
                         self._theta_dot], axis=1).astype(np.float32)

    def reset(self) -> np.ndarray:
        self._reset_idx(np.ones(self.num_envs, dtype=bool))
        self.truncateds = np.zeros(self.num_envs, dtype=bool)
        self.final_obs = self._obs()
        return self._obs()

    def step(self, actions: np.ndarray):
        u = np.clip(np.asarray(actions, np.float64).reshape(self.num_envs),
                    -self.MAX_TORQUE, self.MAX_TORQUE)
        th = ((self._theta + np.pi) % (2 * np.pi)) - np.pi  # angle_normalize
        costs = th ** 2 + 0.1 * self._theta_dot ** 2 + 0.001 * u ** 2
        new_dot = self._theta_dot + (
            3 * self.G / (2 * self.L) * np.sin(self._theta)
            + 3.0 / (self.M * self.L ** 2) * u) * self.DT
        new_dot = np.clip(new_dot, -self.MAX_SPEED, self.MAX_SPEED)
        self._theta = self._theta + new_dot * self.DT
        self._theta_dot = new_dot
        self._steps += 1
        rewards = (-costs).astype(np.float32)
        self._returns += rewards

        truncated = self._steps >= self.MAX_STEPS
        dones = truncated.copy()
        self.truncateds = truncated.copy()
        self.final_obs = self._obs()
        episode_returns = np.full(self.num_envs, np.nan)
        if dones.any():
            episode_returns[dones] = self._returns[dones]
            self._reset_idx(dones)
        return self._obs(), rewards, dones.astype(np.float32), \
            episode_returns


class GymnasiumVecEnv(VectorEnv):
    """Adapter over `gymnasium.make_vec` for everything not built-in."""

    def __init__(self, name: str, num_envs: int = 1, seed: int = 0):
        import gymnasium as gym

        # gymnasium >=1.0 defaults vector envs to NEXT_STEP autoreset,
        # which injects a ghost transition after each episode; force the
        # SAME_STEP contract this module is written against.
        try:
            from gymnasium.vector import AutoresetMode

            self._env = gym.make_vec(
                name, num_envs=num_envs,
                vector_kwargs={"autoreset_mode": AutoresetMode.SAME_STEP})
        except (ImportError, TypeError):
            self._env = gym.make_vec(name, num_envs=num_envs)
        self.num_envs = num_envs
        self.obs_dim = int(np.prod(self._env.single_observation_space.shape))
        self.num_actions = int(self._env.single_action_space.n)
        self._seed = seed
        self._returns = np.zeros(num_envs, dtype=np.float64)

    def reset(self) -> np.ndarray:
        obs, _ = self._env.reset(seed=self._seed)
        self._returns[:] = 0.0
        obs = np.asarray(obs, dtype=np.float32).reshape(self.num_envs, -1)
        self.truncateds = np.zeros(self.num_envs, dtype=bool)
        self.final_obs = obs
        return obs

    def step(self, actions: np.ndarray):
        obs, rew, term, trunc, infos = self._env.step(np.asarray(actions))
        obs = np.asarray(obs, dtype=np.float32).reshape(self.num_envs, -1)
        rew = np.asarray(rew, dtype=np.float32)
        term = np.asarray(term, dtype=bool)
        trunc = np.asarray(trunc, dtype=bool) & ~term
        dones = (term | trunc).astype(np.float32)
        self.truncateds = trunc
        # SAME_STEP autoreset puts the pre-reset observation in infos;
        # fall back to the returned obs (no bootstrap) when absent.
        self.final_obs = obs
        final = infos.get("final_obs", infos.get("final_observation"))
        if final is not None:
            self.final_obs = obs.copy()
            for i, fo in enumerate(final):
                if fo is not None:
                    self.final_obs[i] = np.asarray(
                        fo, dtype=np.float32).reshape(-1)
        self._returns += rew
        episode_returns = np.full(self.num_envs, np.nan)
        finished = dones > 0
        if finished.any():
            episode_returns[finished] = self._returns[finished]
            self._returns[finished] = 0.0
        return obs, rew, dones, episode_returns
