"""Actor classes and handles.

Analogue of the reference's ActorClass/ActorHandle
(ref: python/ray/actor.py:563 ActorClass, :851 `_remote`, :1223 ActorHandle).
Actor method calls are ordered per-caller by default; `max_concurrency` and
async actors relax that (ref: transport/actor_scheduling_queue.h,
concurrency_group_manager.h).
"""
from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Union

from ray_tpu.core.ids import ActorID
from ray_tpu.core.object_ref import ObjectRef
from ray_tpu.core.task_spec import TaskOptions
from ray_tpu.remote_function import _merge_options


def method(*, concurrency_group: Optional[str] = None, **_ignored):
    """Method-level actor options (ref: python/ray/actor.py `ray.method`).

    Currently routes the method to a named concurrency group declared in
    `@remote(concurrency_groups={...})`; the group's pool bounds how many
    calls of its methods run at once, independently of other groups::

        @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
        class A:
            @ray_tpu.method(concurrency_group="io")
            def fetch(self): ...
    """

    def wrap(fn):
        if concurrency_group is not None:
            fn.__ray_tpu_concurrency_group__ = concurrency_group
        return fn

    return wrap


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method '{self._method_name}' cannot be called directly. "
            "Use '.remote(...)' instead."
        )

    def options(self, **updates) -> "ActorMethod":
        m = ActorMethod(self._handle, self._method_name, self._num_returns)
        # Merge over the method-level defaults (num_returns resets to the
        # method's own default, not the actor's creation options) and
        # validate against the full option schema so typos fail loudly.
        base = dataclasses.replace(self._handle._options,
                                   num_returns=self._num_returns)
        m._call_options = _merge_options(base, **updates)
        return m

    def remote(self, *args, **kwargs) -> Union[ObjectRef, List[ObjectRef]]:
        from ray_tpu.api import _global_worker

        worker = _global_worker()
        opts = getattr(self, "_call_options", None)
        if opts is None:
            # Cached: dataclasses.replace per call is measurable on the
            # submission hot path, and the defaults never change.
            opts = getattr(self, "_default_options", None)
            if opts is None:
                opts = dataclasses.replace(self._handle._options,
                                           num_returns=self._num_returns)
                self._default_options = opts
        refs = worker.submit_actor_task(
            self._handle._actor_id, self._method_name, list(args),
            dict(kwargs), opts)
        if opts.num_returns == 1:
            return refs[0]
        return refs

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.api import actor_method_bind

        return actor_method_bind(self._handle, self._method_name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, cls_name: str,
                 options: TaskOptions, method_names: List[str]):
        self._actor_id = actor_id
        self._cls_name = cls_name
        self._options = options
        self._method_names = method_names

    def __getattr__(self, item: str) -> ActorMethod:
        if item.startswith("_"):
            raise AttributeError(item)
        # Cache on the instance: __getattr__ only fires on a miss, so
        # repeated `handle.method` calls reuse one ActorMethod (and its
        # cached options) instead of allocating per call.
        method = ActorMethod(self, item)
        self.__dict__[item] = method
        return method

    def __repr__(self) -> str:
        return f"ActorHandle({self._cls_name}, {self._actor_id.hex()[:16]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._cls_name, self._options,
             self._method_names),
        )

    @property
    def _ray_actor_id(self) -> ActorID:
        return self._actor_id


class ActorClass:
    def __init__(self, cls: type, options: Optional[TaskOptions] = None):
        self._cls = cls
        self._options = options or TaskOptions()
        self.__name__ = cls.__name__
        self.__qualname__ = getattr(cls, "__qualname__", cls.__name__)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            "directly. Use '.remote(...)' instead."
        )

    def options(self, **updates) -> "ActorClass":
        return ActorClass(self._cls, _merge_options(self._options, **updates))

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.api import _global_worker

        worker = _global_worker()
        actor_id = worker.create_actor(self._cls, list(args), dict(kwargs),
                                       self._options)
        methods = [m for m in dir(self._cls) if not m.startswith("__")]
        return ActorHandle(actor_id, self._cls.__name__, self._options,
                           methods)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag.api import actor_class_bind

        return actor_class_bind(self, args, kwargs)
