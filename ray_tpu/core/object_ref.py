"""ObjectRef: a first-class future/handle to a value in the object store.

Analogue of the reference ObjectRef (ref: python/ray/_raylet.pyx ObjectRef;
ownership model in src/ray/core_worker/reference_count.h:61). Each ref knows
its owner (the worker that created it); the owner is the authority for the
object's lifetime and lineage.
"""
from __future__ import annotations

from typing import Optional

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner", "_skip_refcount", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[str] = None,
                 *, _skip_refcount: bool = False,
                 _preregistered: bool = False):
        self._id = object_id
        self._owner = owner  # owner address "host:port" or None for local
        self._skip_refcount = _skip_refcount
        # _preregistered: the creator already counted this ref (e.g. the
        # actor-submit fast path registers all return refs under one
        # lock) — skip the add, keep the __del__ decref.
        if not (_skip_refcount or _preregistered):
            _refcounter_add(self)

    def id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self) -> Optional[str]:
        return self._owner

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __hash__(self) -> int:
        return hash(self._id)

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        # Serializing a ref hands it to another process: the engine's
        # serialize hook PINS the object at its owner (a transit pin) so
        # it cannot be freed before the receiver registers its borrow
        # (ref: reference_count.h borrower bookkeeping — without the
        # pin, an owner that drops its last local ref right after
        # replying frees the object out from under the borrower).
        _refcounter_serialize(self)
        return (_deserialize_ref, (self._id.binary(), self._owner))

    def __del__(self):
        if not self._skip_refcount:
            _refcounter_remove(self)

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu.api import _global_worker

        return _global_worker().as_future(self)

    def __await__(self):
        import asyncio

        return asyncio.wrap_future(self.future()).__await__()


def _deserialize_ref(binary: bytes, owner: Optional[str]) -> ObjectRef:
    return ObjectRef(ObjectID(binary), owner)


# Reference counting hooks — installed by the active engine. Default: no-op.
_refcounter_add = lambda ref: None
_refcounter_remove = lambda ref: None
_refcounter_serialize = lambda ref: None


def install_refcounter(add, remove, serialize=None) -> None:
    global _refcounter_add, _refcounter_remove, _refcounter_serialize
    _refcounter_add = add
    _refcounter_remove = remove
    _refcounter_serialize = serialize or (lambda ref: None)


def uninstall_refcounter() -> None:
    global _refcounter_add, _refcounter_remove, _refcounter_serialize
    _refcounter_add = lambda ref: None
    _refcounter_remove = lambda ref: None
    _refcounter_serialize = lambda ref: None
