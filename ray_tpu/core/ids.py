"""Binary IDs for tasks, objects, actors, nodes, jobs, placement groups.

Design follows the reference ID scheme (ref: src/ray/common/id.h,
python/ray/includes/unique_ids.pxi): fixed-width random binary ids, with
ObjectIDs derived deterministically from the creating TaskID + return index
so that lineage reconstruction can recompute them.
"""
from __future__ import annotations

import hashlib
import itertools
import os
import struct
from typing import ClassVar

# ID generation is on the task-submission hot path; an os.urandom
# syscall per ID costs ~10x a counter. Uniqueness: an 8-byte per-process
# random prefix (re-drawn after fork) + a monotonically increasing
# counter, padded/truncated to the ID size.
_id_prefix: bytes = b""
_id_prefix_pid: int = -1
_id_counter = itertools.count()


def _fast_random_bytes(size: int) -> bytes:
    if size < 12:
        return os.urandom(size)  # too small for prefix+counter
    global _id_prefix, _id_prefix_pid
    pid = os.getpid()
    if pid != _id_prefix_pid:
        _id_prefix = os.urandom(16)
        _id_prefix_pid = pid
    return (_id_prefix[:size - 8]
            + struct.pack("<Q", next(_id_counter)))


class BaseID:
    SIZE: ClassVar[int] = 16
    __slots__ = ("_binary", "_hash")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} must be {self.SIZE} bytes, got {len(binary)}"
            )
        self._binary = binary
        # hash(bytes) directly — no per-id (typename, binary) tuple.
        # Different ID types sharing a hash only costs a bucket probe;
        # __eq__ is type-exact, so correctness is unchanged.
        self._hash = hash(binary)

    @classmethod
    def generate(cls) -> "BaseID":
        return cls(_fast_random_bytes(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(b"\xff" * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    def is_nil(self) -> bool:
        return self._binary == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._binary

    def hex(self) -> str:
        return self._binary.hex()

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._binary == self._binary

    def __hash__(self) -> int:
        return self._hash

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._binary.hex()})"

    def __reduce__(self):
        return (type(self), (self._binary,))


class JobID(BaseID):
    SIZE = 4


class NodeID(BaseID):
    SIZE = 16


class WorkerID(BaseID):
    SIZE = 16


class ActorID(BaseID):
    SIZE = 16


class PlacementGroupID(BaseID):
    SIZE = 16


class TaskID(BaseID):
    SIZE = 16

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        h = hashlib.sha1(b"actor_creation:" + actor_id.binary()).digest()
        return cls(h[: cls.SIZE])


class ObjectID(BaseID):
    SIZE = 20  # 16-byte task id + 4-byte return index

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def from_random(cls) -> "ObjectID":
        return cls(os.urandom(cls.SIZE))

    def task_id(self) -> TaskID:
        return TaskID(self._binary[:16])

    def return_index(self) -> int:
        return int.from_bytes(self._binary[16:], "little")
