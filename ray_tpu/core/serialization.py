"""Object serialization: cloudpickle + out-of-band zero-copy buffers.

TPU-native analogue of the reference's SerializationContext
(ref: python/ray/_private/serialization.py): pickle protocol 5 with
out-of-band buffers so large numpy/arrow payloads are written into the
shared-memory store without an extra copy, and read back zero-copy via mmap.

Wire format (used both for the shm store and chunked DCN transfer):

    magic   u32   "RTPU"
    version u8
    flags   u8    bit0 = payload is a serialized exception
    nbufs   u16
    pkl_len u64
    buf_len u64 * nbufs
    <pickle bytes>
    <64-byte-aligned buffer 0> ...
"""
from __future__ import annotations

import io
import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

MAGIC = 0x52545055
_HEADER = struct.Struct("<IBBHQ")
ALIGN = 64

FLAG_ERROR = 1


def _align(n: int) -> int:
    return (n + ALIGN - 1) & ~(ALIGN - 1)


def serialize(obj: Any, *, is_error: bool = False) -> Tuple[bytes, List[memoryview]]:
    """Serialize to (header+pickle bytes, out-of-band buffers)."""
    buffers: List[pickle.PickleBuffer] = []
    try:
        # Plain pickle first: the C pickler is ~10x cloudpickle and
        # handles the common case (task args/results are data, not
        # code). Two fallbacks to cloudpickle: objects plain pickle
        # can't do at all (closures/lambdas raise), and anything pickled
        # BY REFERENCE into __main__ — resolvable on this driver but not
        # in a worker process, where cloudpickle's by-value pickling is
        # required (same split cloudpickle itself makes).
        pkl = pickle.dumps(obj, protocol=5,
                           buffer_callback=buffers.append)
        if b"__main__" in pkl or b"__mp_main__" in pkl:
            raise ValueError("main-module reference")
    except Exception:  # noqa: BLE001
        buffers.clear()
        pkl = cloudpickle.dumps(obj, protocol=5,
                                buffer_callback=buffers.append)
    views = [b.raw() for b in buffers]
    flags = FLAG_ERROR if is_error else 0
    head = _HEADER.pack(MAGIC, 1, flags, len(views), len(pkl))
    lens = struct.pack(f"<{len(views)}Q", *(len(v) for v in views)) if views else b""
    return head + lens + pkl, views


def serialized_size(meta: bytes, buffers: List[memoryview]) -> int:
    total = len(meta)
    for v in buffers:
        total = _align(total) + len(v)
    return total


def write_to(buf: memoryview, meta: bytes, buffers: List[memoryview]) -> int:
    """Write the full serialized object into `buf`; returns bytes written."""
    off = len(meta)
    buf[:off] = meta
    for v in buffers:
        off = _align(off)
        buf[off : off + len(v)] = v
        off += len(v)
    return off


_PAD64 = bytes(64)


def iov_parts(meta: bytes, buffers: List[memoryview]) -> List[memoryview]:
    """The serialized layout as an iovec — byte-identical to what
    `write_to` produces, but as a list of views the store's direct-write
    fast path hands straight to write() without materializing a
    contiguous copy."""
    parts = [memoryview(meta)]
    off = len(meta)
    for v in buffers:
        pad = _align(off) - off
        if pad:
            parts.append(memoryview(_PAD64)[:pad])
        parts.append(memoryview(v))
        off = _align(off) + len(v)
    return parts


def concat(meta: bytes, buffers: List[memoryview]) -> bytes:
    """Materialize the serialized layout as one contiguous bytes (the
    inline-reply path; large objects should go through put_serialized /
    iov_parts instead — no contiguous intermediate)."""
    if not buffers:
        return meta  # head + pickle, nothing to align
    out = io.BytesIO()
    out.write(meta)
    off = len(meta)
    for v in buffers:
        pad = _align(off) - off
        out.write(b"\x00" * pad)
        out.write(v)
        off = _align(off) + len(v)
    return out.getvalue()


def dumps(obj: Any, *, is_error: bool = False) -> bytes:
    meta, buffers = serialize(obj, is_error=is_error)
    return concat(meta, buffers)


def deserialize(data) -> Any:
    """Deserialize from bytes/memoryview. Zero-copy: out-of-band buffers are
    memoryview slices of `data` (keep the backing mmap alive via the views)."""
    view = memoryview(data)
    magic, version, flags, nbufs, pkl_len = _HEADER.unpack_from(view, 0)
    if magic != MAGIC:
        raise ValueError("corrupt object: bad magic")
    off = _HEADER.size
    lens = struct.unpack_from(f"<{nbufs}Q", view, off) if nbufs else ()
    off += 8 * nbufs
    pkl = view[off : off + pkl_len]
    off += pkl_len
    bufs = []
    for ln in lens:
        off = _align(off)
        bufs.append(view[off : off + ln])
        off += ln
    obj = pickle.loads(pkl, buffers=bufs)
    if flags & FLAG_ERROR:
        raise obj
    return obj


def is_error_payload(data) -> bool:
    view = memoryview(data)
    _, _, flags, _, _ = _HEADER.unpack_from(view, 0)
    return bool(flags & FLAG_ERROR)
