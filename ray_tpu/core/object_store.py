"""Python client for the native shared-memory object store.

Pairs with ray_tpu/native/object_store.cc (the plasma equivalent — ref:
src/ray/object_manager/plasma/client.h). Values are serialized with the
protocol-5 out-of-band format and written straight into the mmap'd object
file; reads deserialize zero-copy from the mapping (numpy arrays alias shm).
"""
from __future__ import annotations

import ctypes
import mmap
import os
import threading
import weakref
from typing import Any, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID

RTS_OK = 0
RTS_ERR_IO = -1
RTS_ERR_EXISTS = -2
RTS_ERR_NOT_FOUND = -3
RTS_ERR_FULL = -4
RTS_ERR_STATE = -5


class ObjectStoreFullError(Exception):
    pass


class ObjectExistsError(Exception):
    pass


def _load_lib() -> ctypes.CDLL:
    from ray_tpu.native.build import library_path

    lib = ctypes.CDLL(library_path())
    lib.rts_connect.restype = ctypes.c_void_p
    lib.rts_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.c_uint64]
    lib.rts_disconnect.argtypes = [ctypes.c_void_p]
    lib.rts_capacity.restype = ctypes.c_uint64
    lib.rts_capacity.argtypes = [ctypes.c_void_p]
    lib.rts_used.restype = ctypes.c_uint64
    lib.rts_used.argtypes = [ctypes.c_void_p]
    lib.rts_num_objects.restype = ctypes.c_uint64
    lib.rts_num_objects.argtypes = [ctypes.c_void_p]
    lib.rts_evict.restype = ctypes.c_uint64
    lib.rts_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rts_create.restype = ctypes.c_int
    lib.rts_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint64,
                               ctypes.POINTER(ctypes.c_int)]
    lib.rts_seal.restype = ctypes.c_int
    lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_abort.restype = ctypes.c_int
    lib.rts_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_get.restype = ctypes.c_int
    lib.rts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.POINTER(ctypes.c_uint64),
                            ctypes.POINTER(ctypes.c_int)]
    lib.rts_release.restype = ctypes.c_int
    lib.rts_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_contains.restype = ctypes.c_int
    lib.rts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_delete.restype = ctypes.c_int
    lib.rts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.rts_list.restype = ctypes.c_uint64
    lib.rts_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64]
    lib.rts_stat.restype = ctypes.c_int
    lib.rts_stat.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.POINTER(ctypes.c_uint32),
                             ctypes.POINTER(ctypes.c_uint64),
                             ctypes.POINTER(ctypes.c_uint32)]
    lib.rts_recycle_bytes.restype = ctypes.c_uint64
    lib.rts_recycle_bytes.argtypes = [ctypes.c_void_p]
    return lib


_lib: Optional[ctypes.CDLL] = None


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


def _direct_write_min() -> int:
    """Size floor for the large-put direct-write fast path (0 = off)."""
    from ray_tpu.core.config import get_config

    return get_config().put_direct_min_bytes


def _write_all(fd: int, parts: List[memoryview]) -> None:
    """Sequential write() of iovec parts into a store file at offset 0.
    The kernel copies straight into (possibly recycled, already-warm)
    tmpfs page cache — the mmap path's per-page fault + zero-fill never
    happens, which is the entire win of the large-put fast path."""
    for part in parts:
        mv = part if part.contiguous else memoryview(bytes(part))
        while len(mv):
            n = os.write(fd, mv)
            mv = mv[n:]


class _StoreState:
    """Shared between an ObjectStore and its outstanding SharedBuffers so the
    native handle is only freed after the last buffer releases (a finalizer
    running after disconnect() must not touch freed memory)."""

    def __init__(self, handle):
        self.handle = handle
        self.live_buffers = 0
        self.closed = False
        self.lock = threading.Lock()

    def buffer_acquired(self):
        with self.lock:
            self.live_buffers += 1

    def buffer_released(self, oid_binary: bytes):
        with self.lock:
            # The handle stays valid until the last buffer releases (close()
            # defers rts_disconnect), so the shared refcount must always be
            # decremented — skipping it would pin the slot forever.
            if self.handle:
                get_lib().rts_release(self.handle, oid_binary)
            self.live_buffers -= 1
            if self.closed and self.live_buffers == 0 and self.handle:
                get_lib().rts_disconnect(self.handle)
                self.handle = None

    def close(self):
        with self.lock:
            self.closed = True
            if self.live_buffers == 0 and self.handle:
                get_lib().rts_disconnect(self.handle)
                self.handle = None


class SharedBuffer:
    """A read-only view over a sealed object's mmap; releases the store ref
    when garbage collected (or released explicitly)."""

    def __init__(self, state: _StoreState, oid: ObjectID, mm: mmap.mmap,
                 size: int):
        self._mm = mm
        self.size = size
        self.view = memoryview(mm)[:size]
        state.buffer_acquired()
        self._finalizer = weakref.finalize(
            self, SharedBuffer._release_static, state, oid.binary(), mm,
            self.view)

    def release(self) -> None:
        self._finalizer()

    @staticmethod
    def _release_static(state: _StoreState, oid_binary: bytes,
                        mm: mmap.mmap, view: memoryview) -> None:
        try:
            view.release()
            mm.close()
        except BufferError:
            pass  # numpy views still alive; mmap closes when they drop
        try:
            state.buffer_released(oid_binary)
        except Exception:
            pass


class FileBuffer:
    """Read-only view over a spilled object file (API-compatible subset of
    SharedBuffer). The OS page cache makes repeat reads cheap."""

    def __init__(self, mm: mmap.mmap, size: int):
        self._mm = mm
        self.size = size
        self.view = memoryview(mm)[:size]

    def release(self) -> None:
        try:
            self.view.release()
            self._mm.close()
        except BufferError:
            pass  # numpy views still alive; mmap closes when they drop


class PartialBuffer:
    """A created-but-unsealed object being filled at offsets: the
    create-then-fill seam of the transfer plane. `write_at` lands chunk
    bytes straight in the store's mmap (or the spill file when shm is
    full) — receivers never accumulate an object on the Python heap.
    `seal()` publishes atomically; `abort()` (also the GC finalizer)
    rolls back so a dropped transfer cannot leak a creating slot.
    """

    def __init__(self, state: _StoreState, oid: ObjectID, size: int,
                 mm: Optional[mmap.mmap], spill_tmp: Optional[str] = None,
                 spill_path: Optional[str] = None):
        self._state = state
        self._oid = oid
        self.size = size
        self._mm = mm
        self.view = memoryview(mm) if mm is not None else memoryview(b"")
        self._spill_tmp = spill_tmp
        self._spill_path = spill_path
        self._done = False
        # Safety net only: the owner is expected to seal or abort
        # explicitly. kCreating slots do self-expire (kStaleCreatingSecs)
        # but that pins `size` bytes of shm for 5 minutes.
        self._finalizer = weakref.finalize(
            self, PartialBuffer._abort_static, state, oid.binary(), mm,
            self.view, spill_tmp)

    def write_at(self, offset: int, data) -> None:
        if self._done:
            raise RuntimeError("write into sealed/aborted PartialBuffer")
        n = len(data)
        if offset < 0 or offset + n > self.size:
            raise ValueError(
                f"chunk [{offset}, {offset + n}) outside object of "
                f"{self.size} bytes")
        self.view[offset:offset + n] = data

    def _close_mapping(self) -> None:
        try:
            self.view.release()
            if self._mm is not None:
                self._mm.close()
        except BufferError:
            pass  # outstanding views; mmap closes when they drop

    def seal(self) -> None:
        if self._done:
            return
        self._done = True
        self._finalizer.detach()
        self._close_mapping()
        if self._spill_tmp is not None:
            os.rename(self._spill_tmp, self._spill_path)
            return
        rc = get_lib().rts_seal(self._state.handle, self._oid.binary())
        if rc != RTS_OK:
            raise RuntimeError(f"rts_seal failed: {rc}")

    def abort(self) -> None:
        if self._done:
            return
        self._done = True
        self._finalizer.detach()
        PartialBuffer._abort_static(self._state, self._oid.binary(),
                                    self._mm, self.view, self._spill_tmp)

    @staticmethod
    def _abort_static(state: _StoreState, oid_binary: bytes,
                      mm: Optional[mmap.mmap], view: memoryview,
                      spill_tmp: Optional[str]) -> None:
        try:
            view.release()
            if mm is not None:
                mm.close()
        except BufferError:
            pass
        if spill_tmp is not None:
            try:
                os.unlink(spill_tmp)
            except OSError:
                pass
            return
        try:
            if state.handle:
                get_lib().rts_abort(state.handle, oid_binary)
        except Exception:  # noqa: BLE001
            pass


class ArenaBuffer:
    """A long-lived writable reservation carved out of the shm store —
    the backing pool of the paged KV-cache block allocator
    (serve/kv_cache.py).

    Rides the same create-then-fill seam as PartialBuffer but closes it
    immediately: the slot is sealed right after creation (the writable
    mmap stays valid across the seal's rename — same inode) and then
    pinned with a reader ref.  Sealing dodges the store's stale-kCreating
    reclaim (kStaleCreatingSecs sweeps unsealed slots under pressure);
    the pin keeps the sealed arena off the LRU eviction list.  release()
    unpins and deletes, returning the store to quiescence — the leak
    guard tests assert used/num_objects return to baseline.

    When shm is full even after eviction the arena falls back to an
    anonymous private mapping (`in_store=False`): the pool still works,
    it just isn't accounted in the store.
    """

    def __init__(self, state: Optional[_StoreState], oid: Optional[ObjectID],
                 mm: mmap.mmap, size: int, in_store: bool):
        self._state = state
        self._oid = oid
        self._mm = mm
        self.size = size
        self.in_store = in_store
        self.view = memoryview(mm)
        if in_store:
            state.buffer_acquired()
        self._finalizer = weakref.finalize(
            self, ArenaBuffer._release_static, state,
            oid.binary() if oid is not None else None, mm, self.view,
            in_store)

    def release(self) -> None:
        self._finalizer()

    @staticmethod
    def _release_static(state: Optional[_StoreState],
                        oid_binary: Optional[bytes], mm: mmap.mmap,
                        view: memoryview, in_store: bool) -> None:
        try:
            view.release()
            mm.close()
        except BufferError:
            pass  # outstanding views; mmap closes when they drop
        if not in_store:
            return
        try:
            # buffer_released drops the rts_get pin; with no other
            # readers the delete frees the slot immediately.
            state.buffer_released(oid_binary)
            if state.handle:
                get_lib().rts_delete(state.handle, oid_binary, 1)
        except Exception:  # noqa: BLE001
            pass


class ObjectStore:
    """One connection to the node-local shm store.

    When the shm arena is full even after LRU eviction (everything pinned),
    puts overflow to per-object files under `<directory>/spill/` — the
    plasma fallback-allocation/spill equivalent (ref: src/ray/raylet/
    local_object_manager.h:41 spill/restore, plasma fallback allocator).
    Reads fall back to the spill directory transparently; since every
    process on a node shares `directory`, spilled objects stay visible to
    the daemon's transfer path and to co-located workers.
    """

    def __init__(self, directory: str, capacity: int = 0,
                 num_slots: int = 65536):
        if capacity <= 0:
            import psutil

            capacity = int(psutil.virtual_memory().total * 0.3)
        self.directory = directory
        self.capacity = capacity
        self.spill_dir = os.path.join(directory, "spill")
        handle = get_lib().rts_connect(directory.encode(), capacity, num_slots)
        if not handle:
            raise RuntimeError(f"Failed to connect to object store at "
                               f"{directory}")
        self._state = _StoreState(handle)

    # -- spill plumbing -------------------------------------------------
    def _spill_path(self, oid: ObjectID) -> str:
        return os.path.join(self.spill_dir, oid.hex())

    def _spill_write(self, oid: ObjectID, write_fn, size: int) -> int:
        """Atomically create a spill file via tmp+rename (rename is the seal:
        readers never observe a partial object)."""
        os.makedirs(self.spill_dir, exist_ok=True)
        path = self._spill_path(oid)
        if os.path.exists(path):
            raise ObjectExistsError(oid.hex())
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w+b") as f:
                if size:
                    f.truncate(size)
                    with mmap.mmap(f.fileno(), size) as mm:
                        view = memoryview(mm)
                        write_fn(view)
                        view.release()
            os.rename(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return size

    def _spill_read(self, oid: ObjectID) -> Optional[FileBuffer]:
        path = self._spill_path(oid)
        try:
            f = open(path, "rb")
        except FileNotFoundError:
            return None
        with f:
            size = os.fstat(f.fileno()).st_size
            if size == 0:
                return FileBuffer(mmap.mmap(-1, 1), 0)
            mm = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        return FileBuffer(mm, size)

    @property
    def _handle(self):
        return self._state.handle

    # -- write path -----------------------------------------------------
    def put_serialized(self, oid: ObjectID, meta: bytes,
                       buffers: List[memoryview]) -> int:
        """Write a pre-serialized object; returns its size in bytes."""
        size = serialization.serialized_size(meta, buffers)
        lib = get_lib()
        fd = ctypes.c_int(-1)
        rc = lib.rts_create(self._handle, oid.binary(), size,
                            ctypes.byref(fd))
        if rc == RTS_ERR_EXISTS:
            raise ObjectExistsError(oid.hex())
        if rc == RTS_ERR_FULL:
            # rts_create already ran LRU eviction internally; everything
            # left in shm is pinned — overflow this object to disk.
            return self._spill_write(
                oid, lambda view: serialization.write_to(view, meta, buffers),
                size)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_create failed: {rc}")
        try:
            if size >= _direct_write_min() > 0:
                # Large-put fast path: hand the kernel the serialized
                # layout as an iovec.  write() copies into the tmpfs page
                # cache directly — no per-page fault + zero-fill like the
                # mmap path (~3x on this host class), and still one copy.
                _write_all(fd.value, serialization.iov_parts(meta, buffers))
            else:
                with mmap.mmap(fd.value, size) as mm:
                    view = memoryview(mm)
                    serialization.write_to(view, meta, buffers)
                    view.release()
        except BaseException:
            os.close(fd.value)
            lib.rts_abort(self._handle, oid.binary())
            raise
        else:
            os.close(fd.value)
        rc = lib.rts_seal(self._handle, oid.binary())
        if rc != RTS_OK:
            raise RuntimeError(f"rts_seal failed: {rc}")
        return size

    def put(self, oid: ObjectID, value: Any, *, is_error: bool = False) -> int:
        meta, buffers = serialization.serialize(value, is_error=is_error)
        return self.put_serialized(oid, meta, buffers)

    def put_raw(self, oid: ObjectID, data: bytes) -> int:
        """Write pre-framed bytes (e.g. received from a remote node)."""
        lib = get_lib()
        fd = ctypes.c_int(-1)
        size = len(data)
        rc = lib.rts_create(self._handle, oid.binary(), size,
                            ctypes.byref(fd))
        if rc == RTS_ERR_EXISTS:
            raise ObjectExistsError(oid.hex())
        if rc == RTS_ERR_FULL:
            def copy(view):
                view[:size] = data

            return self._spill_write(oid, copy, size)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_create failed: {rc}")
        try:
            if size >= _direct_write_min() > 0:
                _write_all(fd.value, [memoryview(data)])
            elif size:
                with mmap.mmap(fd.value, size) as mm:
                    mm[:size] = data
        except BaseException:
            os.close(fd.value)
            lib.rts_abort(self._handle, oid.binary())
            raise
        else:
            os.close(fd.value)
        rc = lib.rts_seal(self._handle, oid.binary())
        if rc != RTS_OK:
            raise RuntimeError(f"rts_seal failed: {rc}")
        return size

    def create_for_receive(self, oid: ObjectID, size: int) -> PartialBuffer:
        """Create an unsealed object and hand back a writable fill-at-
        offset view (the receive side of chunked transfers). Chunks land
        directly in the shm mmap in any order; the caller seals once all
        bytes arrived. Falls back to a spill tmp file when shm is full
        even after eviction (rename-on-seal keeps the atomicity)."""
        lib = get_lib()
        fd = ctypes.c_int(-1)
        rc = lib.rts_create(self._handle, oid.binary(), size,
                            ctypes.byref(fd))
        if rc == RTS_ERR_EXISTS:
            raise ObjectExistsError(oid.hex())
        if rc == RTS_ERR_FULL:
            os.makedirs(self.spill_dir, exist_ok=True)
            path = self._spill_path(oid)
            if os.path.exists(path):
                raise ObjectExistsError(oid.hex())
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w+b") as f:
                mm = None
                if size:
                    f.truncate(size)
                    mm = mmap.mmap(f.fileno(), size)
            return PartialBuffer(self._state, oid, size, mm,
                                 spill_tmp=tmp, spill_path=path)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_create failed: {rc}")
        try:
            mm = mmap.mmap(fd.value, size) if size else None
        except BaseException:
            os.close(fd.value)
            lib.rts_abort(self._handle, oid.binary())
            raise
        os.close(fd.value)
        return PartialBuffer(self._state, oid, size, mm)

    def create_arena(self, oid: ObjectID, size: int) -> ArenaBuffer:
        """Reserve a long-lived writable arena in shm (the paged
        KV-cache block pool).  create -> mmap -> seal -> pin: see
        ArenaBuffer for why the seam is closed immediately.  Falls back
        to an anonymous mapping when shm is exhausted."""
        if size <= 0:
            raise ValueError("arena size must be positive")
        lib = get_lib()
        fd = ctypes.c_int(-1)
        rc = lib.rts_create(self._handle, oid.binary(), size,
                            ctypes.byref(fd))
        if rc == RTS_ERR_EXISTS:
            raise ObjectExistsError(oid.hex())
        if rc == RTS_ERR_FULL:
            return ArenaBuffer(None, None, mmap.mmap(-1, size), size,
                               in_store=False)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_create failed: {rc}")
        try:
            mm = mmap.mmap(fd.value, size)
        except BaseException:
            os.close(fd.value)
            lib.rts_abort(self._handle, oid.binary())
            raise
        os.close(fd.value)
        rc = lib.rts_seal(self._handle, oid.binary())
        if rc != RTS_OK:
            mm.close()
            lib.rts_abort(self._handle, oid.binary())
            raise RuntimeError(f"rts_seal failed: {rc}")
        # Reader pin: a sealed refcount-0 object is LRU-evictable; the
        # arena must survive store pressure for the engine's lifetime.
        sz = ctypes.c_uint64(0)
        pin_fd = ctypes.c_int(-1)
        rc = lib.rts_get(self._handle, oid.binary(), ctypes.byref(sz),
                         ctypes.byref(pin_fd))
        if rc != RTS_OK:
            mm.close()
            raise RuntimeError(f"rts_get failed pinning arena: {rc}")
        os.close(pin_fd.value)
        return ArenaBuffer(self._state, oid, mm, size, in_store=True)

    # -- read path ------------------------------------------------------
    def get_buffer(self, oid: ObjectID) -> Optional[SharedBuffer]:
        lib = get_lib()
        size = ctypes.c_uint64(0)
        fd = ctypes.c_int(-1)
        rc = lib.rts_get(self._handle, oid.binary(), ctypes.byref(size),
                         ctypes.byref(fd))
        if rc == RTS_ERR_NOT_FOUND:
            return self._spill_read(oid)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_get failed: {rc}")
        try:
            mm = mmap.mmap(fd.value, size.value, prot=mmap.PROT_READ)
        finally:
            os.close(fd.value)
        return SharedBuffer(self._state, oid, mm, size.value)

    def get(self, oid: ObjectID) -> Tuple[Any, Optional[SharedBuffer]]:
        """Deserialize; the returned SharedBuffer must stay alive as long as
        zero-copy views into it (numpy arrays) are in use."""
        buf = self.get_buffer(oid)
        if buf is None:
            raise KeyError(oid.hex())
        value = serialization.deserialize(buf.view)
        return value, buf

    # -- management -----------------------------------------------------
    def stat(self, oid: ObjectID) -> Optional[dict]:
        """Slot introspection without touching refcount/LRU: dict with
        state ('creating'/'sealed'), size, refcount — or None when the
        store has no live slot (spilled objects report via the file)."""
        state = ctypes.c_uint32(0)
        size = ctypes.c_uint64(0)
        refcount = ctypes.c_uint32(0)
        rc = get_lib().rts_stat(self._handle, oid.binary(),
                                ctypes.byref(state), ctypes.byref(size),
                                ctypes.byref(refcount))
        if rc != RTS_OK:
            try:
                sz = os.stat(self._spill_path(oid)).st_size
            except OSError:
                return None
            return {"state": "sealed", "size": sz, "refcount": 0,
                    "spilled": True}
        return {"state": {1: "creating", 2: "sealed"}.get(
                    state.value, str(state.value)),
                "size": size.value, "refcount": refcount.value,
                "spilled": False}

    def contains(self, oid: ObjectID) -> bool:
        if get_lib().rts_contains(self._handle, oid.binary()):
            return True
        return os.path.exists(self._spill_path(oid))

    def delete(self, oid: ObjectID, force: bool = False) -> bool:
        ok = get_lib().rts_delete(self._handle, oid.binary(),
                                  1 if force else 0) == RTS_OK
        try:
            os.unlink(self._spill_path(oid))
            ok = True
        except OSError:
            pass
        return ok

    @property
    def spilled_bytes(self) -> int:
        try:
            with os.scandir(self.spill_dir) as it:
                return sum(e.stat().st_size for e in it
                           if e.is_file() and ".tmp." not in e.name)
        except FileNotFoundError:
            return 0

    def evict(self, nbytes: int) -> int:
        return get_lib().rts_evict(self._handle, nbytes)

    def list_objects(self, max_objects: int = 100000) -> List[ObjectID]:
        buf = ctypes.create_string_buffer(20 * max_objects)
        n = get_lib().rts_list(self._handle, buf, max_objects)
        return [ObjectID(bytes(buf[i * 20:(i + 1) * 20])) for i in range(n)]

    @property
    def used(self) -> int:
        return get_lib().rts_used(self._handle)

    @property
    def num_objects(self) -> int:
        return get_lib().rts_num_objects(self._handle)

    @property
    def recycle_bytes(self) -> int:
        """Bytes parked in the warm-file recycle pool (deleted large
        objects whose tmpfs files — and faulted-in pages — are kept for
        the next large create).  Not part of ``used``: no live object
        backs them, but they do count toward the store's tmpfs footprint
        and eviction drains them first."""
        return get_lib().rts_recycle_bytes(self._handle)

    def disconnect(self) -> None:
        self._state.close()

    @staticmethod
    def destroy(directory: str) -> None:
        """Remove every object file + index for a store directory."""
        import shutil

        shutil.rmtree(directory, ignore_errors=True)
