"""Python client for the native shared-memory object store.

Pairs with ray_tpu/native/object_store.cc (the plasma equivalent — ref:
src/ray/object_manager/plasma/client.h). Values are serialized with the
protocol-5 out-of-band format and written straight into the mmap'd object
file; reads deserialize zero-copy from the mapping (numpy arrays alias shm).
"""
from __future__ import annotations

import ctypes
import mmap
import os
import threading
import weakref
from typing import Any, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID

RTS_OK = 0
RTS_ERR_IO = -1
RTS_ERR_EXISTS = -2
RTS_ERR_NOT_FOUND = -3
RTS_ERR_FULL = -4
RTS_ERR_STATE = -5


class ObjectStoreFullError(Exception):
    pass


class ObjectExistsError(Exception):
    pass


def _load_lib() -> ctypes.CDLL:
    from ray_tpu.native.build import library_path

    lib = ctypes.CDLL(library_path())
    lib.rts_connect.restype = ctypes.c_void_p
    lib.rts_connect.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                ctypes.c_uint64]
    lib.rts_disconnect.argtypes = [ctypes.c_void_p]
    lib.rts_capacity.restype = ctypes.c_uint64
    lib.rts_capacity.argtypes = [ctypes.c_void_p]
    lib.rts_used.restype = ctypes.c_uint64
    lib.rts_used.argtypes = [ctypes.c_void_p]
    lib.rts_num_objects.restype = ctypes.c_uint64
    lib.rts_num_objects.argtypes = [ctypes.c_void_p]
    lib.rts_evict.restype = ctypes.c_uint64
    lib.rts_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.rts_create.restype = ctypes.c_int
    lib.rts_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint64,
                               ctypes.POINTER(ctypes.c_int)]
    lib.rts_seal.restype = ctypes.c_int
    lib.rts_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_abort.restype = ctypes.c_int
    lib.rts_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_get.restype = ctypes.c_int
    lib.rts_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                            ctypes.POINTER(ctypes.c_uint64),
                            ctypes.POINTER(ctypes.c_int)]
    lib.rts_release.restype = ctypes.c_int
    lib.rts_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_contains.restype = ctypes.c_int
    lib.rts_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rts_delete.restype = ctypes.c_int
    lib.rts_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.rts_list.restype = ctypes.c_uint64
    lib.rts_list.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64]
    return lib


_lib: Optional[ctypes.CDLL] = None


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load_lib()
    return _lib


class _StoreState:
    """Shared between an ObjectStore and its outstanding SharedBuffers so the
    native handle is only freed after the last buffer releases (a finalizer
    running after disconnect() must not touch freed memory)."""

    def __init__(self, handle):
        self.handle = handle
        self.live_buffers = 0
        self.closed = False
        self.lock = threading.Lock()

    def buffer_acquired(self):
        with self.lock:
            self.live_buffers += 1

    def buffer_released(self, oid_binary: bytes):
        with self.lock:
            # The handle stays valid until the last buffer releases (close()
            # defers rts_disconnect), so the shared refcount must always be
            # decremented — skipping it would pin the slot forever.
            if self.handle:
                get_lib().rts_release(self.handle, oid_binary)
            self.live_buffers -= 1
            if self.closed and self.live_buffers == 0 and self.handle:
                get_lib().rts_disconnect(self.handle)
                self.handle = None

    def close(self):
        with self.lock:
            self.closed = True
            if self.live_buffers == 0 and self.handle:
                get_lib().rts_disconnect(self.handle)
                self.handle = None


class SharedBuffer:
    """A read-only view over a sealed object's mmap; releases the store ref
    when garbage collected (or released explicitly)."""

    def __init__(self, state: _StoreState, oid: ObjectID, mm: mmap.mmap,
                 size: int):
        self._mm = mm
        self.size = size
        self.view = memoryview(mm)[:size]
        state.buffer_acquired()
        self._finalizer = weakref.finalize(
            self, SharedBuffer._release_static, state, oid.binary(), mm,
            self.view)

    def release(self) -> None:
        self._finalizer()

    @staticmethod
    def _release_static(state: _StoreState, oid_binary: bytes,
                        mm: mmap.mmap, view: memoryview) -> None:
        try:
            view.release()
            mm.close()
        except BufferError:
            pass  # numpy views still alive; mmap closes when they drop
        try:
            state.buffer_released(oid_binary)
        except Exception:
            pass


class FileBuffer:
    """Read-only view over a spilled object file (API-compatible subset of
    SharedBuffer). The OS page cache makes repeat reads cheap."""

    def __init__(self, mm: mmap.mmap, size: int):
        self._mm = mm
        self.size = size
        self.view = memoryview(mm)[:size]

    def release(self) -> None:
        try:
            self.view.release()
            self._mm.close()
        except BufferError:
            pass  # numpy views still alive; mmap closes when they drop


class ObjectStore:
    """One connection to the node-local shm store.

    When the shm arena is full even after LRU eviction (everything pinned),
    puts overflow to per-object files under `<directory>/spill/` — the
    plasma fallback-allocation/spill equivalent (ref: src/ray/raylet/
    local_object_manager.h:41 spill/restore, plasma fallback allocator).
    Reads fall back to the spill directory transparently; since every
    process on a node shares `directory`, spilled objects stay visible to
    the daemon's transfer path and to co-located workers.
    """

    def __init__(self, directory: str, capacity: int = 0,
                 num_slots: int = 65536):
        if capacity <= 0:
            import psutil

            capacity = int(psutil.virtual_memory().total * 0.3)
        self.directory = directory
        self.capacity = capacity
        self.spill_dir = os.path.join(directory, "spill")
        handle = get_lib().rts_connect(directory.encode(), capacity, num_slots)
        if not handle:
            raise RuntimeError(f"Failed to connect to object store at "
                               f"{directory}")
        self._state = _StoreState(handle)

    # -- spill plumbing -------------------------------------------------
    def _spill_path(self, oid: ObjectID) -> str:
        return os.path.join(self.spill_dir, oid.hex())

    def _spill_write(self, oid: ObjectID, write_fn, size: int) -> int:
        """Atomically create a spill file via tmp+rename (rename is the seal:
        readers never observe a partial object)."""
        os.makedirs(self.spill_dir, exist_ok=True)
        path = self._spill_path(oid)
        if os.path.exists(path):
            raise ObjectExistsError(oid.hex())
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "w+b") as f:
                if size:
                    f.truncate(size)
                    with mmap.mmap(f.fileno(), size) as mm:
                        view = memoryview(mm)
                        write_fn(view)
                        view.release()
            os.rename(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return size

    def _spill_read(self, oid: ObjectID) -> Optional[FileBuffer]:
        path = self._spill_path(oid)
        try:
            f = open(path, "rb")
        except FileNotFoundError:
            return None
        with f:
            size = os.fstat(f.fileno()).st_size
            if size == 0:
                return FileBuffer(mmap.mmap(-1, 1), 0)
            mm = mmap.mmap(f.fileno(), size, prot=mmap.PROT_READ)
        return FileBuffer(mm, size)

    @property
    def _handle(self):
        return self._state.handle

    # -- write path -----------------------------------------------------
    def put_serialized(self, oid: ObjectID, meta: bytes,
                       buffers: List[memoryview]) -> int:
        """Write a pre-serialized object; returns its size in bytes."""
        size = serialization.serialized_size(meta, buffers)
        lib = get_lib()
        fd = ctypes.c_int(-1)
        rc = lib.rts_create(self._handle, oid.binary(), size,
                            ctypes.byref(fd))
        if rc == RTS_ERR_EXISTS:
            raise ObjectExistsError(oid.hex())
        if rc == RTS_ERR_FULL:
            # rts_create already ran LRU eviction internally; everything
            # left in shm is pinned — overflow this object to disk.
            return self._spill_write(
                oid, lambda view: serialization.write_to(view, meta, buffers),
                size)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_create failed: {rc}")
        try:
            with mmap.mmap(fd.value, size) as mm:
                view = memoryview(mm)
                serialization.write_to(view, meta, buffers)
                view.release()
        except BaseException:
            os.close(fd.value)
            lib.rts_abort(self._handle, oid.binary())
            raise
        else:
            os.close(fd.value)
        rc = lib.rts_seal(self._handle, oid.binary())
        if rc != RTS_OK:
            raise RuntimeError(f"rts_seal failed: {rc}")
        return size

    def put(self, oid: ObjectID, value: Any, *, is_error: bool = False) -> int:
        meta, buffers = serialization.serialize(value, is_error=is_error)
        return self.put_serialized(oid, meta, buffers)

    def put_raw(self, oid: ObjectID, data: bytes) -> int:
        """Write pre-framed bytes (e.g. received from a remote node)."""
        lib = get_lib()
        fd = ctypes.c_int(-1)
        size = len(data)
        rc = lib.rts_create(self._handle, oid.binary(), size,
                            ctypes.byref(fd))
        if rc == RTS_ERR_EXISTS:
            raise ObjectExistsError(oid.hex())
        if rc == RTS_ERR_FULL:
            def copy(view):
                view[:size] = data

            return self._spill_write(oid, copy, size)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_create failed: {rc}")
        try:
            if size:
                with mmap.mmap(fd.value, size) as mm:
                    mm[:size] = data
        except BaseException:
            os.close(fd.value)
            lib.rts_abort(self._handle, oid.binary())
            raise
        else:
            os.close(fd.value)
        rc = lib.rts_seal(self._handle, oid.binary())
        if rc != RTS_OK:
            raise RuntimeError(f"rts_seal failed: {rc}")
        return size

    # -- read path ------------------------------------------------------
    def get_buffer(self, oid: ObjectID) -> Optional[SharedBuffer]:
        lib = get_lib()
        size = ctypes.c_uint64(0)
        fd = ctypes.c_int(-1)
        rc = lib.rts_get(self._handle, oid.binary(), ctypes.byref(size),
                         ctypes.byref(fd))
        if rc == RTS_ERR_NOT_FOUND:
            return self._spill_read(oid)
        if rc != RTS_OK:
            raise RuntimeError(f"rts_get failed: {rc}")
        try:
            mm = mmap.mmap(fd.value, size.value, prot=mmap.PROT_READ)
        finally:
            os.close(fd.value)
        return SharedBuffer(self._state, oid, mm, size.value)

    def get(self, oid: ObjectID) -> Tuple[Any, Optional[SharedBuffer]]:
        """Deserialize; the returned SharedBuffer must stay alive as long as
        zero-copy views into it (numpy arrays) are in use."""
        buf = self.get_buffer(oid)
        if buf is None:
            raise KeyError(oid.hex())
        value = serialization.deserialize(buf.view)
        return value, buf

    # -- management -----------------------------------------------------
    def contains(self, oid: ObjectID) -> bool:
        if get_lib().rts_contains(self._handle, oid.binary()):
            return True
        return os.path.exists(self._spill_path(oid))

    def delete(self, oid: ObjectID, force: bool = False) -> bool:
        ok = get_lib().rts_delete(self._handle, oid.binary(),
                                  1 if force else 0) == RTS_OK
        try:
            os.unlink(self._spill_path(oid))
            ok = True
        except OSError:
            pass
        return ok

    @property
    def spilled_bytes(self) -> int:
        try:
            with os.scandir(self.spill_dir) as it:
                return sum(e.stat().st_size for e in it
                           if e.is_file() and ".tmp." not in e.name)
        except FileNotFoundError:
            return 0

    def evict(self, nbytes: int) -> int:
        return get_lib().rts_evict(self._handle, nbytes)

    def list_objects(self, max_objects: int = 100000) -> List[ObjectID]:
        buf = ctypes.create_string_buffer(20 * max_objects)
        n = get_lib().rts_list(self._handle, buf, max_objects)
        return [ObjectID(bytes(buf[i * 20:(i + 1) * 20])) for i in range(n)]

    @property
    def used(self) -> int:
        return get_lib().rts_used(self._handle)

    @property
    def num_objects(self) -> int:
        return get_lib().rts_num_objects(self._handle)

    def disconnect(self) -> None:
        self._state.close()

    @staticmethod
    def destroy(directory: str) -> None:
        """Remove every object file + index for a store directory."""
        import shutil

        shutil.rmtree(directory, ignore_errors=True)
