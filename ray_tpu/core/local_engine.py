"""In-process execution engine (``init(local_mode=True)``).

Implements the full task/actor/object semantics of the distributed runtime in
one process: ordered actor queues, concurrency groups, retries, named actors,
reference-counted object lifetimes. It is both a debugging mode (like the
reference's local mode) and the executable spec the distributed engine mirrors
(ref semantics: src/ray/core_worker/core_worker.h:291,
transport/actor_scheduling_queue.h ordered dispatch).
"""
from __future__ import annotations

import asyncio
import inspect
import threading
import time
from collections import defaultdict
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import serialization
from ray_tpu.core.ids import ActorID, ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef, install_refcounter, uninstall_refcounter
from ray_tpu.core.task_spec import TaskOptions
from ray_tpu import exceptions as rexc


class _Store:
    """In-memory object store with completion futures."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._data: Dict[ObjectID, bytes] = {}
        self._events: Dict[ObjectID, threading.Event] = {}

    def _event(self, oid: ObjectID) -> threading.Event:
        with self._lock:
            ev = self._events.get(oid)
            if ev is None:
                ev = self._events[oid] = threading.Event()
            return ev

    def put(self, oid: ObjectID, payload: bytes) -> None:
        with self._lock:
            self._data[oid] = payload
            ev = self._events.setdefault(oid, threading.Event())
            self._cond.notify_all()
        ev.set()

    def put_if_absent(self, oid: ObjectID, payload: bytes) -> None:
        with self._lock:
            if oid in self._data:
                return
            self._data[oid] = payload
            ev = self._events.setdefault(oid, threading.Event())
            self._cond.notify_all()
        ev.set()

    def wait_any(self, oids, timeout: Optional[float]) -> None:
        """Block until any of `oids` is present (or timeout)."""
        with self._lock:
            self._cond.wait_for(
                lambda: any(o in self._data for o in oids), timeout)

    def contains(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._data

    def wait(self, oid: ObjectID, timeout: Optional[float]) -> bool:
        return self._event(oid).wait(timeout)

    def get(self, oid: ObjectID) -> bytes:
        with self._lock:
            return self._data[oid]

    def delete(self, oid: ObjectID) -> None:
        with self._lock:
            self._data.pop(oid, None)
            self._events.pop(oid, None)


class _LocalActor:
    """One actor instance with an ordered dispatch queue.

    Default: a single thread executes calls in submission order (the
    reference's SequentialActorSubmitQueue semantics). With
    ``max_concurrency > 1`` calls run on a pool that wide; async actors run
    coroutine methods concurrently on a dedicated event loop.
    """

    def __init__(self, actor_id: ActorID, cls: type, args, kwargs,
                 options: TaskOptions):
        self.actor_id = actor_id
        self.options = options
        self.name = options.name
        self.dead = False
        self.death_reason = ""
        self._cls = cls
        self._is_async = any(
            inspect.iscoroutinefunction(m)
            for _, m in inspect.getmembers(cls, inspect.isfunction)
        )
        maxc = max(1, options.max_concurrency)
        if self._is_async and options.max_concurrency == 1:
            maxc = 1000  # async actors default to high concurrency
        self._pool = ThreadPoolExecutor(
            max_workers=maxc, thread_name_prefix=f"actor-{actor_id.hex()[:8]}"
        )
        self._order_lock = threading.Lock()
        # Return ids of calls accepted but not yet stored — failed with
        # ActorDiedError if the actor is killed first (otherwise get() on
        # them would hang forever).
        self.pending_lock = threading.Lock()
        self.pending_returns: set = set()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        if self._is_async:
            self._loop = asyncio.new_event_loop()
            t = threading.Thread(target=self._loop.run_forever, daemon=True)
            t.start()
        # Construct synchronously so creation errors surface on first call.
        self.instance = None
        self.creation_error: Optional[BaseException] = None
        try:
            self.instance = cls(*args, **kwargs)
            # Same compiled-DAG escape hatch the distributed worker
            # installs (the reference's `__ray_call__`).
            inst = self.instance

            def __raytpu_apply__(fn, *a, **kw):
                return fn(inst, *a, **kw)

            try:
                inst.__raytpu_apply__ = __raytpu_apply__
            except AttributeError:
                pass
        except BaseException as e:  # noqa: BLE001
            self.creation_error = e

    def submit(self, method_name: str, args, kwargs, run_and_store) -> None:
        if self._is_async and self._loop is not None:
            method = getattr(self.instance, method_name, None)
            if method is not None and inspect.iscoroutinefunction(method):
                # Resolve blocking arg dependencies on a pool thread, then run
                # the coroutine on the actor's event loop — never block the
                # loop itself (it may be the producer of those very args).
                def dispatch():
                    coro = run_and_store(self, method_name, args, kwargs,
                                         is_async=True)
                    if coro is not None:
                        asyncio.run_coroutine_threadsafe(coro, self._loop)

                self._pool.submit(dispatch)
                return
        if self.options.max_concurrency <= 1 and not self._is_async:
            # ordered execution: single queue
            self._pool.submit(self._run_ordered, method_name, args, kwargs,
                              run_and_store)
        else:
            self._pool.submit(run_and_store, self, method_name, args, kwargs)

    def _run_ordered(self, method_name, args, kwargs, run_and_store):
        with self._order_lock:
            run_and_store(self, method_name, args, kwargs)

    def kill(self, reason: str = "killed via kill()"):
        self.dead = True
        self.death_reason = reason
        self._pool.shutdown(wait=False, cancel_futures=True)
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)


class LocalCoreWorker:
    """Single-process implementation of the core-worker interface."""

    def __init__(self, num_cpus: Optional[int] = None):
        import os

        self.node_id_hex = "local"
        self.address = "local"
        self._store = _Store()
        ncpu = num_cpus or os.cpu_count() or 8
        self._pool = ThreadPoolExecutor(max_workers=max(4, ncpu),
                                        thread_name_prefix="task")
        self._actors: Dict[ActorID, _LocalActor] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        # RLock: _ref_removed can re-enter from ObjectRef.__del__ during GC
        # triggered while _ref_added already holds the lock on this thread.
        self._lock = threading.RLock()
        self._refcounts: Dict[ObjectID, int] = defaultdict(int)
        self._cancelled: set = set()
        self._pgs: Dict[str, dict] = {}
        install_refcounter(self._ref_added, self._ref_removed)

    # ---- reference counting ----
    def _ref_added(self, ref: ObjectRef) -> None:
        with self._lock:
            self._refcounts[ref.id()] += 1

    def _ref_removed(self, ref: ObjectRef) -> None:
        with self._lock:
            n = self._refcounts.get(ref.id())
            if n is None:
                return
            if n <= 1:
                del self._refcounts[ref.id()]
                self._store.delete(ref.id())
            else:
                self._refcounts[ref.id()] = n - 1

    # ---- object API ----
    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.from_random()
        self._store.put(oid, serialization.dumps(value))
        return ObjectRef(oid, self.address)

    def _store_value(self, oid: ObjectID, value: Any) -> None:
        self._store.put(oid, serialization.dumps(value))

    def _store_error(self, oid: ObjectID, err: BaseException) -> None:
        try:
            payload = serialization.dumps(err, is_error=True)
        except Exception:
            # The user exception (or its cause) is unpicklable — degrade to
            # traceback text so the caller still gets an error, not a hang.
            if isinstance(err, rexc.TaskError):
                stripped = rexc.TaskError(err.function_name, err.traceback_str,
                                          cause=None, pid=err.pid,
                                          node_id=err.node_id)
            else:
                stripped = rexc.TaskError("<unknown>", repr(err))
            payload = serialization.dumps(stripped, is_error=True)
        self._store.put(oid, payload)

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        deadline = None if timeout is None else time.monotonic() + timeout
        out = []
        for ref in refs:
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                raise rexc.GetTimeoutError(
                    f"Get timed out waiting for {ref.hex()}")
            if not self._store.wait(ref.id(), remaining):
                raise rexc.GetTimeoutError(
                    f"Get timed out waiting for {ref.hex()}")
            out.append(serialization.deserialize(self._store.get(ref.id())))
        return out

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: List[ObjectRef] = []
        pending = list(refs)
        while True:
            still = []
            for r in pending:
                if self._store.contains(r.id()):
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns or not pending:
                break
            remaining = None if deadline is None else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                break
            self._store.wait_any([r.id() for r in pending], remaining)
            if deadline is not None and time.monotonic() >= deadline:
                break
        ready = ready[:num_returns]
        return ready, [r for r in refs if r not in ready]

    def as_future(self, ref: ObjectRef) -> Future:
        fut: Future = Future()

        def waiter():
            try:
                fut.set_result(self.get([ref])[0])
            except BaseException as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=waiter, daemon=True).start()
        return fut

    # ---- task API ----
    def submit_task(self, func, args, kwargs, options: TaskOptions
                    ) -> List[ObjectRef]:
        task_id = TaskID.generate()
        num_returns = options.num_returns
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(1, num_returns + 1)]
        fname = getattr(func, "__qualname__", str(func))

        def run(attempt=0):
            if task_id in self._cancelled:
                for oid in return_ids:
                    self._store_error(oid, rexc.TaskCancelledError(fname))
                return
            try:
                rargs, rkwargs = self._resolve_args(args, kwargs)
                result = func(*rargs, **rkwargs)
                if inspect.iscoroutine(result):
                    result = asyncio.run(result)
                self._store_returns(return_ids, num_returns, result, fname)
            except BaseException as e:  # noqa: BLE001
                # Application exceptions only retry when the user opted in
                # (ref: retry_exceptions in ray_option_utils); system errors
                # (worker/node death) are retried by the distributed engine.
                retryable = options.retry_exceptions and not isinstance(
                    e, rexc.RayTpuError)
                if retryable and attempt < options.max_retries:
                    self._pool.submit(run, attempt + 1)
                    return
                err = rexc.TaskError.from_exception(e, fname)
                for oid in return_ids:
                    self._store_error(oid, err)

        self._pool.submit(run)
        return [ObjectRef(oid, self.address) for oid in return_ids]

    def submit_streaming_task(self, func, args, kwargs,
                              options: TaskOptions):
        """num_returns="streaming" in local mode: the generator runs on
        the pool, each yield is stored immediately, and the returned
        iterator hands out refs as they land (same consumable-before-
        completion contract as the distributed engine)."""
        import queue as _queue

        from ray_tpu.core.streaming import LocalRefGenerator

        task_id = TaskID.generate()
        fname = getattr(func, "__qualname__", str(func))
        items: "_queue.Queue" = _queue.Queue()

        def run():
            try:
                rargs, rkwargs = self._resolve_args(args, kwargs)
                result = func(*rargs, **rkwargs)
                if not inspect.isgenerator(result):
                    raise rexc.TaskError(
                        fname, f"num_returns='streaming' task returned "
                               f"{type(result).__name__}, not a generator")
                n = 0
                for v in result:
                    n += 1
                    oid = ObjectID.for_task_return(task_id, n)
                    self._store_value(oid, v)
                    items.put(("item", ObjectRef(oid, self.address)))
                items.put(("end", None))
            except BaseException as e:  # noqa: BLE001
                items.put(("err", e if isinstance(e, rexc.RayTpuError)
                           else rexc.TaskError.from_exception(e, fname)))

        self._pool.submit(run)
        return LocalRefGenerator(items)

    def _store_returns(self, return_ids, num_returns, result, fname):
        if num_returns == 1:
            self._store_value(return_ids[0], result)
        else:
            if not isinstance(result, (tuple, list)) or len(result) != num_returns:
                err = rexc.TaskError(
                    fname, f"Task declared num_returns={num_returns} but "
                    f"returned {type(result).__name__}")
                for oid in return_ids:
                    self._store_error(oid, err)
                return
            for oid, item in zip(return_ids, result):
                self._store_value(oid, item)

    def _resolve_args(self, args, kwargs):
        def resolve(v):
            if isinstance(v, ObjectRef):
                return self.get([v])[0]
            return v

        return [resolve(a) for a in args], {k: resolve(v)
                                            for k, v in kwargs.items()}

    def cancel(self, ref: ObjectRef, force: bool = False,
               recursive: bool = True) -> None:
        self._cancelled.add(ref.id().task_id())

    # ---- actor API ----
    def create_actor(self, cls, args, kwargs, options: TaskOptions) -> ActorID:
        actor_id = ActorID.generate()
        if options.name:
            key = (options.namespace or "default", options.name)
            with self._lock:
                if key in self._named_actors:
                    raise ValueError(
                        f"Actor name '{options.name}' already taken in "
                        f"namespace '{key[0]}'")
                self._named_actors[key] = actor_id
        rargs, rkwargs = self._resolve_args(args, kwargs)
        actor = _LocalActor(actor_id, cls, rargs, rkwargs, options)
        with self._lock:
            self._actors[actor_id] = actor
        return actor_id

    def submit_actor_task(self, actor_id: ActorID, method_name: str, args,
                          kwargs, options: TaskOptions) -> List[ObjectRef]:
        if options.num_returns == "streaming":
            raise NotImplementedError(
                "actor-method streaming is not supported in local_mode "
                "(task streaming is; or run a real cluster)")
        task_id = TaskID.generate()
        num_returns = options.num_returns
        return_ids = [ObjectID.for_task_return(task_id, i)
                      for i in range(1, num_returns + 1)]
        with self._lock:
            actor = self._actors.get(actor_id)
        if actor is None or actor.dead:
            reason = actor.death_reason if actor else "actor not found"
            err = rexc.ActorDiedError(actor_id.hex(), reason)
            for oid in return_ids:
                self._store_error(oid, err)
            return [ObjectRef(oid, self.address) for oid in return_ids]

        with actor.pending_lock:
            actor.pending_returns.update(return_ids)

        def finish():
            with actor.pending_lock:
                actor.pending_returns.difference_update(return_ids)

        def run_and_store(actor: _LocalActor, method_name, args, kwargs,
                          is_async=False):
            fname = f"{actor._cls.__name__}.{method_name}"
            try:
                if actor.creation_error is not None:
                    raise rexc.ActorDiedError(
                        actor_id.hex(),
                        f"creation failed: {actor.creation_error!r}")
                if actor.dead:
                    raise rexc.ActorDiedError(actor_id.hex(),
                                              actor.death_reason)
                rargs, rkwargs = self._resolve_args(args, kwargs)
                method = getattr(actor.instance, method_name)
                result = method(*rargs, **rkwargs)
                if inspect.iscoroutine(result):
                    if is_async:
                        async def _await_and_store():
                            try:
                                res = await result
                                self._store_returns(return_ids, num_returns,
                                                    res, fname)
                            except BaseException as e:  # noqa: BLE001
                                err = rexc.ActorError.from_exception(e, fname)
                                for oid in return_ids:
                                    self._store_error(oid, err)
                            finally:
                                finish()
                        return _await_and_store()
                    result = asyncio.run(result)
                self._store_returns(return_ids, num_returns, result, fname)
                finish()
            except BaseException as e:  # noqa: BLE001
                if isinstance(e, rexc.RayTpuError):
                    err = e
                else:
                    err = rexc.ActorError.from_exception(e, fname)
                for oid in return_ids:
                    self._store_error(oid, err)
                finish()
            return None

        actor.submit(method_name, args, kwargs, run_and_store)
        return [ObjectRef(oid, self.address) for oid in return_ids]

    def get_actor(self, name: str, namespace: Optional[str]) -> ActorID:
        key = (namespace or "default", name)
        with self._lock:
            aid = self._named_actors.get(key)
        if aid is None:
            raise ValueError(f"Failed to look up actor '{name}'")
        return aid

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        with self._lock:
            actor = self._actors.get(actor_id)
        if actor is not None:
            actor.kill()
            if actor.name:
                self._named_actors.pop(
                    (actor.options.namespace or "default", actor.name), None)
            # Fail every accepted-but-unfinished call so get() raises instead
            # of hanging (a completed call's result is never overwritten).
            with actor.pending_lock:
                pending = list(actor.pending_returns)
                actor.pending_returns.clear()
            err = rexc.ActorDiedError(actor_id.hex(), actor.death_reason)
            payload = serialization.dumps(err, is_error=True)
            for oid in pending:
                self._store.put_if_absent(oid, payload)

    def actor_state(self, actor_id: ActorID) -> str:
        with self._lock:
            a = self._actors.get(actor_id)
        if a is None:
            return "DEAD"
        return "DEAD" if a.dead else "ALIVE"

    # ---- placement groups (trivially satisfied on one node) ----
    def create_placement_group(self, pg_id, bundles, strategy,
                               name=None, detached=False) -> None:
        with self._lock:
            self._pgs[pg_id.hex()] = {
                "pg_id": pg_id.hex(), "state": "CREATED",
                "nodes": ["local"] * len(bundles), "bundles": bundles,
                "strategy": strategy,
            }

    def get_placement_group(self, pg_id):
        with self._lock:
            return self._pgs.get(pg_id.hex())

    def remove_placement_group(self, pg_id) -> None:
        with self._lock:
            pg = self._pgs.get(pg_id.hex())
            if pg is not None:
                pg["state"] = "REMOVED"

    def list_placement_groups(self):
        with self._lock:
            return list(self._pgs.values())

    # ---- internal KV (in-process; mirrors the GCS KV surface) ----
    def kv_put(self, namespace, key, value, overwrite: bool = True) -> bool:
        kv = getattr(self, "_kv_store", None)
        if kv is None:
            kv = self._kv_store = {}
        k = (bytes(namespace), bytes(key))
        if not overwrite and k in kv:
            return False
        kv[k] = value
        return True

    def kv_get(self, namespace, key):
        return getattr(self, "_kv_store", {}).get(
            (bytes(namespace), bytes(key)))

    def kv_del(self, namespace, key) -> bool:
        return getattr(self, "_kv_store", {}).pop(
            (bytes(namespace), bytes(key)), None) is not None

    def kv_keys(self, namespace, prefix: bytes = b"") -> list:
        ns = bytes(namespace)
        return [k for (n, k) in getattr(self, "_kv_store", {})
                if n == ns and k.startswith(prefix)]

    # ---- lifecycle ----
    def shutdown(self) -> None:
        uninstall_refcounter()
        for a in list(self._actors.values()):
            a.kill("shutdown")
        self._pool.shutdown(wait=False, cancel_futures=True)

    # ---- cluster introspection ----
    def cluster_resources(self) -> Dict[str, float]:
        import os

        return {"CPU": float(os.cpu_count() or 8)}

    def available_resources(self) -> Dict[str, float]:
        return self.cluster_resources()

    def nodes(self) -> List[Dict[str, Any]]:
        return [{"NodeID": "local", "Alive": True,
                 "Resources": self.cluster_resources()}]
