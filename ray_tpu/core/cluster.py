"""Cluster bootstrap: start or connect to a head node.

Analogue of the reference's node bootstrap (ref: python/ray/_private/node.py
start_head_processes :1315, start_ray_processes :1344).
"""
from __future__ import annotations

from typing import Optional


def connect_or_start(address: Optional[str] = None, **kwargs):
    try:
        from ray_tpu.core.distributed.driver import connect_or_start_cluster
    except ImportError as e:
        raise NotImplementedError(
            "The multi-process cluster runtime is not available in this "
            "build; use ray_tpu.init(local_mode=True)."
        ) from e

    return connect_or_start_cluster(address=address, **kwargs)
