"""Central config/flag registry.

TPU-native analogue of the reference's RAY_CONFIG knob system
(ref: src/ray/common/ray_config_def.h — 218 knobs, each overridable via an
env var). Every knob here can be overridden with `RAY_TPU_<NAME>` in the
environment; values are parsed to the declared type.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Any


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    t = type(default)
    if t is bool:
        return raw.lower() in ("1", "true", "yes")
    if t is int:
        return int(raw)
    if t is float:
        return float(raw)
    return raw


@dataclasses.dataclass
class Config:
    # ---- control plane ----
    # GCS-equivalent server port (0 = pick a free port).
    gcs_port: int = 0
    # Storage backend for control-plane state: "memory" (default, like the
    # reference's gcs_storage="memory") or a file path for persistence.
    gcs_storage: str = "memory"
    # Health-check cadence (ref: ray_config_def.h:841-843 — 5s initial delay,
    # 3s period, failure threshold).
    health_check_initial_delay_ms: int = 5000
    health_check_period_ms: int = 3000
    health_check_failure_threshold: int = 5
    # How long raylets may take to reconnect to a restarted control plane.
    gcs_rpc_server_reconnect_timeout_s: int = 60
    # ---- cluster-state syncer (syncer.py; ref: ray_syncer.proto:62 —
    # versioned delta sync replaces full-state heartbeats) ----
    # Delta sync on/off (off => legacy full-state heartbeats + 1 Hz
    # list_nodes view polls).
    syncer_enabled: bool = True
    # Coalescing window between delta pushes: local changes batch into at
    # most one wire message per interval.
    syncer_report_interval_ms: int = 100
    # Idle nodes piggyback liveness on the sync channel with a tiny
    # keepalive at this cadence (must undercut health_check_period_ms *
    # health_check_failure_threshold or idle nodes get marked dead).
    syncer_keepalive_ms: int = 2000
    # GCS fan-out coalescing: node changes batch into at most one
    # cluster-view broadcast per interval.
    syncer_broadcast_interval_ms: int = 200
    # While the sync channel is healthy the legacy heartbeat degrades to
    # a slow fallback: its period is multiplied by this factor.
    syncer_heartbeat_fallback_factor: float = 5.0
    # Cap for the heartbeat/syncer retry backoff when the GCS is down.
    heartbeat_backoff_cap_s: float = 8.0

    # ---- node daemon / scheduling ----
    # Hybrid scheduling policy threshold: prefer the local node until its
    # critical resource utilization crosses this fraction, then spill to the
    # top-k least-utilized nodes (ref: policy/hybrid_scheduling_policy.h:26-49).
    scheduler_spread_threshold: float = 0.5
    scheduler_top_k_fraction: float = 0.2
    scheduler_top_k_absolute: int = 1
    # Worker pool
    num_workers_soft_limit: int = 0  # 0 => num_cpus
    worker_lease_timeout_ms: int = 30000
    idle_worker_killing_time_threshold_ms: int = 1000
    worker_register_timeout_s: int = 30
    # ---- worker zygote / prestart (ref: worker_pool.h:347
    # PrestartWorkers + idle pool; worker_zygote.py here) ----
    # Fork workers from a pre-imported zygote template instead of cold
    # subprocess spawns (RAY_TPU_ZYGOTE_ENABLED=0 to disable; containers
    # and foreign-python runtime envs always cold-spawn).
    zygote_enabled: bool = True
    # Distinct per-runtime-env-key zygotes kept alive (LRU beyond this).
    zygote_max: int = 4
    # Extra comma-separated modules the zygote pre-imports (must be
    # fork-safe: no import-time threads/sockets).
    zygote_preload: str = ""
    # How long a fork request may wait for a just-launched zygote's
    # socket before the spawn falls back to a cold Popen.
    zygote_boot_wait_s: float = 5.0
    # Backlog-driven prestart: when >= watermark default-env lease
    # requests are queued, warm workers are started ahead of grants, up
    # to the warm-pool cap (0 => num_workers_soft_limit).
    worker_prestart_enabled: bool = True
    zygote_prestart_watermark: int = 1
    zygote_warm_pool_cap: int = 0
    # GCS-side actor creations in flight at once (ref:
    # gcs_actor_scheduler.h leases many actors concurrently): a serial
    # loop caps creation at 1/start_actor-latency; the bound keeps a
    # burst from flooding daemons with more concurrent fork+boot
    # pipelines than hosts can absorb.
    actor_schedule_concurrency: int = 8
    # Object transfer chunk size over DCN (ref: ray_config_def.h:352 — 5 MiB).
    object_transfer_chunk_bytes: int = 5 * 1024 * 1024
    # ---- object transfer plane (transfer.py; RAY_TPU_TRANSFER_*) ----
    # Per-pull in-flight chunk budget in BYTES (not chunks): the window
    # striped across all replica sources. Also the receiver's heap
    # high-water bound — chunks land direct-to-shm, only in-flight
    # frames live on the Python heap.
    transfer_window_bytes: int = 64 * 1024 * 1024
    # Concurrent chunk fetches pipelined per source within the window.
    transfer_per_source_inflight: int = 2
    # Per-chunk RPC deadline; also how long a relay serve waits for a
    # not-yet-landed range of an in-flight broadcast object.
    transfer_chunk_timeout_s: float = 30.0
    # Abandoned receive partials (pusher/parent died mid-transfer) are
    # aborted after this long, freeing their store reservation.
    transfer_partial_ttl_s: float = 300.0
    # Relay-tree fan-out for 1->N broadcast pre-staging: each node
    # serves at most this many children, so the owner's uplink carries
    # fanout*size instead of N*size.
    transfer_broadcast_fanout: int = 2
    # Chunk RPCs a push/relay keeps in flight toward one peer.
    transfer_push_pipeline: int = 4
    # Kill switch: serve chunk payloads as raw frames (zero-copy);
    # 0 falls back to the legacy bytes-through-pickle path.
    transfer_raw_frames: bool = True

    # ---- streaming data plane (data/streaming; RAY_TPU_DATA_STREAM_*) ----
    # Default Dataset execution path: streaming operator graph with a
    # bytes-windowed backpressure budget. 0 falls back to the legacy
    # block-materializing executor in data/execution.py.
    data_stream_enabled: bool = True
    # Total bytes of operator output the whole pipeline may hold
    # un-consumed before upstream submission stalls (the global window).
    data_stream_window_bytes: int = 128 * 1024 * 1024
    # Per-operator cap on output bytes in flight (produced but not yet
    # consumed downstream); an operator at its cap stalls — the stall
    # seconds are accounted per operator in Dataset.stats().
    data_stream_op_inflight_bytes: int = 64 * 1024 * 1024
    # Device-prefetch depth for iter_jax_batches: batches resident
    # host->HBM ahead of compute (double buffering at 2).
    data_stream_prefetch_depth: int = 2
    # Relay-tree fan-out for streaming all-to-all shuffle pre-staging;
    # 0 inherits transfer_broadcast_fanout.
    data_stream_shuffle_fanout: int = 0
    # Store used/capacity fraction above which the backpressure budget
    # shrinks and over-budget submissions spill to disk-backed store
    # space instead of stalling forever.
    data_stream_spill_threshold: float = 0.8
    # A byte-stalled operator raises BackpressureTimeout after this
    # long with no forward progress anywhere in the pipeline.
    data_stream_stall_timeout_s: float = 120.0

    # ---- compiled execution plane (task lanes + cross-host channels) ----
    # Pre-leased task lanes: after `task_lane_min_calls` submissions of
    # the same (function, resources, runtime-env) signature the lease is
    # kept warm and pinned, and subsequent calls ride compact raw-frame
    # deltas straight into the pinned worker's executor queue
    # (RAY_TPU_TASK_LANE_ENABLED=0 restores per-call leasing).
    task_lane_enabled: bool = True
    task_lane_min_calls: int = 3
    # Calls in flight on one pinned lane before new submissions spill
    # back to the normal lease/scheduler path (backpressure bound).
    # Kept small on purpose: a lane pipelines the low-concurrency
    # submit+wait pattern, while a large burst should fan out across
    # the worker pool instead of serializing behind one pinned worker.
    task_lane_max_inflight: int = 8
    # Idle pinned lanes release their worker after this long so the
    # pool can reap it (mirrors idle_worker_killing_time_threshold_ms).
    task_lane_idle_s: float = 2.0
    # Channel spin-wait poll backoff bounds, in MICROSECONDS. Once the
    # backoff saturates at the max the waiter also sched_yield()s so a
    # busy peer on the same core can make progress.
    channel_backoff_us_min: float = 1.0
    channel_backoff_us_max: float = 200.0
    # CompiledDag.teardown() wait on stage loops before raising with
    # the straggler list.
    dag_teardown_timeout_s: float = 10.0

    # ---- object store ----
    # Per-node shared-memory store capacity. 0 => 30% of system RAM
    # (matches the reference's default plasma sizing).
    object_store_memory: int = 0
    # Inline small objects in task replies instead of the shm store
    # (ref: max_direct_call_object_size, 100 KiB).
    max_inline_object_size: int = 100 * 1024
    # Fallback directory when /dev/shm is exhausted.
    object_spilling_dir: str = "/tmp/ray_tpu_spill"
    object_spilling_threshold: float = 0.8
    # Large-put direct-write fast path: puts of at least this many bytes
    # land in the store file via write() (kernel page-cache copy — no
    # per-page fault + zero-fill like the mmap path pays, ~3x on tmpfs)
    # and their deleted files park in the native store's bounded
    # warm-file recycle pool for the next large create. 0 disables the
    # fast path (always mmap+copy).
    put_direct_min_bytes: int = 1024 * 1024

    # ---- ownership / lineage ----
    # Keep lineage for reconstruction while refs exist
    # (ref: ray_config_def.h:145 lineage_pinning_enabled, 1 GiB cap :158).
    lineage_pinning_enabled: bool = True
    max_lineage_bytes: int = 1024 * 1024 * 1024
    task_max_retries: int = 3
    actor_max_restarts: int = 0

    # ---- observability ----
    # Prometheus text endpoint on each node daemon (0 = disabled);
    # RAY_TPU_METRICS_EXPORT_PORT=8090 enables :8090/metrics.
    metrics_export_port: int = 0
    # Federated Prometheus endpoint on the GCS (0 = disabled): one
    # exposition merging every node's syncer-shipped metric snapshot,
    # node-labelled (RAY_TPU_METRICS_GCS_EXPORT_PORT).
    metrics_gcs_export_port: int = 0
    # Per-service/method RPC instrumentation (queue-wait + handler
    # latency histograms, inflight gauges, bytes counters) on RpcServer
    # and both clients. RAY_TPU_METRICS_RPC_ENABLED=0 is the bench
    # kill switch the observability-overhead probe flips.
    metrics_rpc_enabled: bool = True
    # EventLoopThread lag probe cadence (0 disables): a sleep(interval)
    # measures its own overshoot — the Python analogue of the
    # reference's instrumented asio event loops.
    metrics_loop_probe_ms: int = 250
    # How often a node piggybacks a full metric snapshot on its syncer
    # push (0 disables federation; the cadence is deliberately much
    # slower than the delta interval — snapshots are the big payload).
    metrics_sync_interval_ms: int = 5000
    # Task events flushed to the GCS sink for the state API/timeline.
    # 1s coalescing window (matches the reference's flush interval):
    # the window size bounds staleness, not volume — volume is bounded
    # by the ring.
    task_events_enabled: bool = True
    task_events_flush_ms: int = 1000
    # Worker-side unflushed-event backstop when the GCS is unreachable:
    # the TaskEventBuffer ring never grows past this many attempts
    # (oldest dropped, per-kind drop counters — execution never blocks).
    task_events_max_buffer: int = 10000
    # Opt-in profile events (object transfers, user profiling spans)
    # riding the same bounded pipeline (RAY_TPU_TASK_EVENTS_PROFILE=1).
    task_events_profile: bool = False
    # GCS-side per-job storage cap: oldest attempts evicted first, with
    # eviction counts surfaced through the state API.
    task_events_max_per_job: int = 10000
    # Finished jobs keep their task events this long before GC frees
    # the storage (0 = GC at the first sweep after job completion).
    task_events_finished_job_ttl_s: float = 300.0
    # Per-task resource attribution: the executor wraps each attempt
    # with thread CPU-time + RSS delta/peak probes and ships them on the
    # attempt's task-event record (RAY_TPU_TASK_EVENTS_RESOURCES=0 is
    # the bench kill switch the attribution_overhead probe flips).
    task_events_resources: bool = True
    # Opt-in JAX device-memory attribution per attempt (reads
    # device.memory_stats() around the task body — a device runtime
    # call, so strictly opt-in: RAY_TPU_TASK_EVENTS_DEVICE_MEM=1).
    task_events_device_mem: bool = False
    # ---- diagnosis plane (signal-safe stack dumps + hung-task
    # watchdog; profiling.py + the Diagnosis GCS service) ----
    # Workers register faulthandler on SIGUSR1 at boot so the daemon can
    # extract all-thread tracebacks even when the GIL is held by a
    # thread stuck in native code (RAY_TPU_STACK_DUMP_ENABLED=0 off).
    stack_dump_enabled: bool = True
    # RUNNING attempts older than this with no progress are flagged
    # hung: one rate-limited stack dump is auto-captured and attached
    # to the attempt's task-event record (0 disables the watchdog).
    hang_threshold_s: float = 300.0
    # Watchdog poll cadence (each tick asks busy workers for their
    # running attempts with a short deadline).
    hang_poll_interval_s: float = 2.0
    # Auto-captured dumps are truncated to this many bytes before they
    # ride the task-event pipeline (bounded record size).
    hang_dump_max_bytes: int = 32768
    # Global floor between auto-captures on one daemon: a mass hang must
    # not turn the watchdog into a signal storm.
    hang_dump_min_interval_s: float = 30.0
    # Opt-in distributed tracing: span context rides TaskSpecs, spans
    # flush into the TaskEvents sink (ref: ray.init tracing hooks,
    # util/tracing/tracing_helper.py).
    tracing_enabled: bool = False
    # Node memory monitor (ref: src/ray/common/memory_monitor.h:52 —
    # refresh cadence; 0 disables) + usage fraction above which the
    # daemon kills workers, newest task lease first (ref LIFO-retriable
    # policy, raylet/worker_killing_policy.h:64).
    memory_monitor_refresh_ms: int = 250
    memory_usage_threshold: float = 0.95
    # GCS load attribution: every GCS RPC carries its caller's identity
    # (node id + component — syncer/serve-gauges/task-events/scheduler/
    # client) and the GCS accumulates per-service x per-component
    # request/bytes/handler-time shares (`ray-tpu gcs top`). The shares
    # are the measure-then-shard evidence for the GCS sharding arc.
    # RAY_TPU_GCS_ATTRIBUTION_ENABLED=0 is the bench kill switch the
    # gcs_attribution_overhead probe flips.
    gcs_attribution_enabled: bool = True
    # Wall budget for a single GCS handler: any handler exceeding it is
    # logged (method + caller + args digest) and journaled so slow-path
    # regressions name their caller (RAY_TPU_GCS_SLOW_HANDLER_MS; 0
    # disables the audit; read once at GCS start).
    gcs_slow_handler_ms: float = 100.0
    # GCS event-loop audit cadence: a sleep(interval) on the GCS's own
    # loop measures its overshoot (lag) and samples the asyncio task
    # backlog + KV/store sizes into gcs-labelled gauges (0 disables).
    gcs_loop_audit_ms: int = 500
    # Cluster flight recorder: a bounded, PersistentStore-durable
    # journal of state transitions (node join/death, failover, drain +
    # KV migration, autoscale/elastic resizes, PG repair) queryable via
    # `ray-tpu events` / state.cluster_events() and surviving GCS
    # restart (RAY_TPU_GCS_FLIGHT_RECORDER_ENABLED=0 disables).
    gcs_flight_recorder_enabled: bool = True
    # In-memory + durable journal bound: oldest entries evicted (and
    # deleted from the store) past this many.
    gcs_flight_max_events: int = 4096

    # ---- placement groups / gang scheduling ----
    # Two-phase gang reserve (ref: gcs_placement_group_scheduler.h:274
    # prepare/commit): a PREPAREd bundle the GCS never commits (GCS
    # crash, peer-node prepare failure) auto-expires on the daemon after
    # this long and its resources return to the pool — the timeout-
    # bounded rollback that keeps a half-placed gang from leaking.
    pg_prepare_ttl_s: float = 30.0
    # On bundle COMMIT the daemon pre-warms one pool worker per bundle
    # so gang start rides ~3ms zygote forks instead of cold spawns
    # (RAY_TPU_PG_PREWARM_ENABLED=0 disables).
    pg_prewarm_enabled: bool = True

    # ---- elastic training plane (train/elastic.py) ----
    # How long the elastic supervisor waits for a replacement bundle
    # (gang back to CREATED) after a rank dies/hangs before it shrinks
    # the gang to the largest feasible world size.
    elastic_replace_timeout_s: float = 30.0
    # Capped exponential backoff + jitter between gang restarts
    # (RAY_TPU_ELASTIC_BACKOFF_*; FailureConfig fields override).
    elastic_backoff_initial_s: float = 0.5
    elastic_backoff_max_s: float = 15.0
    elastic_backoff_multiplier: float = 2.0
    # Fraction of the delay randomized away (0.2 => +/-20%).
    elastic_backoff_jitter: float = 0.2
    # Cadence of the shrunk supervisor's capacity probe for growing the
    # gang back toward the target world size.
    elastic_grow_check_s: float = 10.0

    # ---- train-plane observability (train/observability.py;
    # RAY_TPU_TRAIN_OBS_*) ----
    # Kill switch for the whole train-plane observability stack:
    # per-step phase attribution, per-rank gauge federation, step
    # spans, and the GCS TrainRunState aggregator's inputs.
    train_obs_enabled: bool = True
    # Cadence of the per-rank gauge push (worker -> local node daemon
    # -> syncer -> GCS). Rides the existing serve-gauge report path.
    train_obs_push_s: float = 1.0
    # Node-daemon TTL sweep for per-(run, rank) train gauges: a rank
    # that stops pushing (dead, SIGSTOPped) ages out of the node's
    # synced state after this long, but stays in the GCS aggregator's
    # retained view (marked stale) for blame attribution.
    train_obs_gauge_ttl_s: float = 30.0
    # Step window for cross-rank skew: the per-rank gauges carry mean
    # step time over the last N steps; the GCS computes p99/p50 across
    # ranks from those windows.
    train_obs_window_steps: int = 20
    # Step spans emitted per rank per attempt before span minting stops
    # (bounds trace volume for long runs; the shared tracing ring
    # buffer also caps at 10k records). 0 disables step spans entirely.
    train_obs_trace_steps: int = 512
    # Peak accelerator FLOP/s used as the MFU denominator when
    # ScalingConfig.flops_per_step is set. 0 => report achieved FLOP/s
    # only and skip the MFU estimate.
    train_obs_peak_flops: float = 0.0

    # ---- serving plane (paged KV cache engine; serve/llm.py,
    # serve/kv_cache.py — RAY_TPU_KV_BLOCK_* / RAY_TPU_SERVE_*) ----
    # Tokens per KV block. Small blocks waste less HBM on short tails
    # but deepen block tables; 16 matches the vLLM default.
    kv_block_size: int = 16
    # Blocks in the pool (block 0 is the reserved null block and never
    # allocated). 0 => derived from the engine's num_slots * max_len
    # budget so paged and fixed-slot engines reserve equal HBM.
    kv_block_count: int = 0
    # Refcounted prefix-block sharing + copy-on-write (vLLM automatic
    # prefix caching at block granularity). 0 disables: every request
    # prefills from scratch.
    kv_block_prefix_sharing: bool = True
    # Prompt tokens admitted per engine tick during prefill: long
    # prompts prefill in chunks interleaved with decode bursts so
    # active streams' inter-token latency stays bounded.
    serve_prefill_chunk: int = 128
    # Per-request streaming token queue bound: a consumer that falls
    # this many tokens behind has its stream dropped with an explicit
    # error instead of growing replica RSS without limit.
    serve_stream_queue_max: int = 1024
    # Prompt-lookup speculative decoding on the paged engine: default
    # draft window K for engines/deployments that don't pass
    # speculation_k explicitly. 0/1 disables; >= 2 verifies K
    # candidates (1 carried token + K-1 n-gram proposals) per tick in
    # one width-K device call. Exact under greedy decoding.
    serve_speculation_k: int = 0
    # Trailing n-gram length the drafter matches against each slot's
    # own context (prompt + generated tokens) to mine proposals.
    serve_speculation_ngram: int = 2
    # ---- decode on rails (PR: compiled-DAG serving hot loop) ----
    # Stream token frames over the compiled-DAG channel plane instead of
    # per-batch stream_next RPCs: the handle pre-creates a shm ring on
    # its own node and the replica's stream drain runs as a pinned rails
    # stage whose frames ride versioned channel writes (same-host mmap,
    # cross-host RemoteChannelWriter push through the reader node's
    # daemon). Kill switch: off => every stream admits on the ordinary
    # RPC pull path; on-stream failures always spill there too.
    serve_rails_enabled: bool = True
    # Ring capacity per rails stream (bytes).
    serve_rails_capacity_bytes: int = 1 << 20
    # Per-replica rails lane width: concurrent pinned stream stages.
    # Attach requests beyond this spill to the RPC pull path at
    # admission time (never mid-stream).
    serve_rails_max_streams: int = 32
    # Handle-side ring poll slice; a slice that yields no frame
    # rate-limits a replica liveness probe (serve_rails_probe_s) so a
    # SIGKILLed replica surfaces as a resume, not a silent hang.
    serve_rails_tick_s: float = 0.2
    serve_rails_probe_s: float = 1.0
    # Daemon-side TTL for per-replica serve gauges: a replica that
    # stopped pushing (crash, scale-down) ages out of the syncer's
    # "serve" entry instead of pinning stale queue depth.
    serve_gauge_ttl_s: float = 10.0
    # Controller-side TTL for handle-pushed autoscale stats (the
    # fallback signal when the syncer view is absent): entries from a
    # handle process that exited between pushes expire instead of
    # flapping the replica target.
    serve_autoscale_stats_ttl_s: float = 5.0
    # ---- serving-plane robustness (PR: fault-tolerant serving) ----
    # Handle-side retry budget for replica-death/draining failures:
    # attempts (total tries) and capped exponential backoff + jitter
    # between them, mirroring the elastic-train knobs.  Also bounds the
    # number of mid-stream failover resumes per streaming response.
    serve_retry_max: int = 3
    serve_retry_backoff_initial_s: float = 0.05
    serve_retry_backoff_max_s: float = 2.0
    serve_retry_backoff_multiplier: float = 2.0
    serve_retry_backoff_jitter: float = 0.2
    # Graceful drain on downscale/redeploy: a retiring replica stops
    # admission, keeps serving in-flight streams up to this long, then
    # exits; whatever remains migrates-by-recompute through the handle
    # resume path.
    serve_drain_timeout_s: float = 30.0
    # HTTP proxy admission bound: requests beyond this many in flight
    # are shed with 503 + Retry-After instead of queueing without limit.
    serve_proxy_max_inflight: int = 256
    # Per-request deadline on proxied unary calls and per-pull deadline
    # on proxied/handle streams (replaces the old hardcoded 120 s).
    serve_request_deadline_s: float = 120.0
    # Per-tick wall budget for the controller's concurrent replica
    # health probes (shared deadline across the bounded gather, not
    # per-replica).
    serve_health_timeout_s: float = 10.0
    # ---- serving-plane observability (PR: request observability) ----
    # Per-request serve tracing: the proxy/handle mint a trace context
    # (trace id == request id) and every hop — proxy admission, handle
    # routing, replica queue, engine admission, prefill chunks, decode
    # bursts, stream pulls, failover resumes — records a span into the
    # GCS TaskEvents sink (`ray-tpu serve trace <request-id>`).  On by
    # default (spans are dict appends off the device path); kill switch
    # RAY_TPU_SERVE_TRACE_ENABLED=0 also disables the engines'
    # per-token latency attribution.
    serve_trace_enabled: bool = True
    # Cadence of the replica/proxy worker-process metrics push to the
    # local node daemon (the daemon folds worker registry dumps into
    # its syncer federation payload so serve TTFT/ITL histograms and
    # KV-cache counters appear in `ray-tpu metrics --federated`).
    serve_metrics_push_s: float = 2.0
    # ---- disaggregated serving (PR: disagg plane; serve/disagg.py) ----
    # Knob families: RAY_TPU_SERVE_DISAGG_* (prefill/decode split),
    # RAY_TPU_SERVE_PREFIX_REGISTRY_* (cluster-wide prefix registry),
    # RAY_TPU_SERVE_KV_MIGRATE_* (live KV migration on drain).
    # Prefill/decode split: paged replicas offload long-prompt prefill
    # to dedicated prefill actors and adopt the returned KV frames into
    # their block pool instead of recomputing. Off by default: the
    # split only pays for itself when long prompts interfere with
    # decode ITL.
    serve_disagg_enabled: bool = False
    # Prompts shorter than this many tokens prefill locally even when
    # disagg is on (the frame round-trip costs more than the compute).
    serve_disagg_prompt_threshold: int = 64
    # Dedicated prefill actors per engine pool (keyed by
    # config/block-size/max-len so frames always fit the adopter).
    serve_disagg_prefill_actors: int = 1
    # Cluster-wide prefix registry: replicas publish block-aligned
    # prefix digests over the gauge/syncer path and the handle routes
    # prefix-warm requests to the replica already holding those blocks.
    serve_prefix_registry_enabled: bool = True
    # Per-replica cap on published digests (newest-registered win) so
    # the gauge payload stays bounded on prefix-heavy workloads.
    serve_prefix_registry_max_entries: int = 512
    # Live KV migration on drain: a draining replica exports each
    # in-flight stream's KV blocks as a migration ticket; the resumed
    # stream adopts them on the new replica instead of recomputing the
    # whole context (recompute stays the fallback when the ticket is
    # missing, stale, or oversized).
    serve_kv_migrate_enabled: bool = True
    # Tickets whose KV frame exceeds this many bytes are not published
    # (the resume falls back to recompute rather than bloating the GCS
    # KV store with multi-MB blobs).
    serve_kv_migrate_inline_max_bytes: int = 4194304
    # Grace window the draining replica waits after publishing tickets
    # so handles observe the failure and resume elsewhere before the
    # process exits.
    serve_kv_migrate_linger_s: float = 2.0
    # Tickets older than this are treated as stale and ignored on
    # consume (left-over tickets are also deleted on read).
    serve_kv_migrate_ttl_s: float = 60.0

    # ---- client bootstrap / process-local paths ----
    # Cluster address used by ray_tpu.init() and the CLI when none is
    # passed explicitly ("host:port"; empty = start a local cluster).
    # The supervisor exports RAY_TPU_ADDRESS into worker environments, so
    # this knob is also the in-cluster handoff channel.
    address: str = ""
    # Directory for per-node daemon/worker logs (empty = the session
    # temp dir under /tmp/ray_tpu).
    log_dir: str = ""
    # Explicit path to the native object-store plasma library; empty =
    # build/discover next to the package (native/build.py).
    store_lib: str = ""
    # Mirror driver worker stdout/stderr lines back to the driver
    # process (the reference's log_to_driver).
    log_to_driver: bool = True
    # fsync the GCS persistence WAL on every append. Durable by default;
    # turn off for throughput when the control-plane store is scratch.
    gcs_fsync: bool = True
    # ---- workflow plane ----
    # Root directory for workflow checkpoint storage.
    workflow_storage: str = "/tmp/ray_tpu_workflows"
    # ---- usage stats (opt-in, off by default like the reference's
    # RAY_USAGE_STATS_ENABLED gate) ----
    usage_stats_enabled: bool = False
    # Report endpoint; empty disables the network hop (local file only).
    usage_stats_url: str = ""
    # Local spool file for usage reports (empty = session temp dir).
    usage_stats_path: str = ""
    # ---- serve controller bootstrap ----
    # Grace window for replica actors to come up before the controller
    # declares a deployment failed.
    serve_startup_grace_s: float = 600.0

    # ---- timeouts ----
    get_timeout_milliseconds: int = 0  # 0 = no timeout
    rpc_connect_timeout_s: int = 30
    actor_creation_timeout_s: int = 120

    # ---- TPU topology ----
    # Resource name used for TPU chips (ref: _private/accelerators/tpu.py
    # resource name "TPU") and the slice-head gang resource pattern
    # "TPU-{pod_type}-head" (ref: tpu.py:382).
    tpu_resource_name: str = "TPU"
    tpu_head_resource_format: str = "TPU-{pod_type}-head"

    def __post_init__(self):
        for f in dataclasses.fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))


_config: Config | None = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config()
    return _config


def reset_config() -> None:
    global _config
    _config = None
