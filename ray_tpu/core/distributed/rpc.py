"""gRPC plumbing: generic pickle-codec services without protoc codegen.

Role parity with the reference RPC framework (ref: src/ray/rpc/grpc_server.h:85,
grpc_client.h:92, client_call.h:188 — completion-queue wrappers around
generated stubs). Here services are plain Python objects whose public async
methods become unary-unary RPCs at `/raytpu.<Service>/<method>`; requests and
responses are dicts serialized with cloudpickle. Streaming methods (name
prefixed `stream_`) become unary-stream RPCs for chunked object transfer and
pub/sub long-polls.
"""
from __future__ import annotations

import asyncio
import inspect
import pickle
import threading
from typing import Any, Callable, Dict, Optional

import cloudpickle
import grpc
import grpc.aio


def _ser(obj: Any) -> bytes:
    """Binary framing for RPC payloads: plain pickle first (RPC messages
    are dicts of primitives/bytes — functions and user objects ride inside
    pre-serialized blobs), cloudpickle only as the fallback for the rare
    payload plain pickle can't handle. ~3-5x faster on the hot path."""
    try:
        return pickle.dumps(obj, protocol=5)
    except Exception:  # noqa: BLE001 — closures, local classes, ...
        return cloudpickle.dumps(obj, protocol=5)


def _de(data: bytes) -> Any:
    return pickle.loads(data)


GRPC_OPTIONS = [
    ("grpc.max_send_message_length", 512 * 1024 * 1024),
    ("grpc.max_receive_message_length", 512 * 1024 * 1024),
    ("grpc.so_reuseport", 0),
]


class RpcError(Exception):
    pass


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, services: Dict[str, Any]):
        self._services = services

    def service(self, handler_call_details):
        path = handler_call_details.method  # "/raytpu.Svc/method"
        try:
            _, svc_method = path.split("/raytpu.", 1)
            svc_name, method_name = svc_method.split("/", 1)
        except ValueError:
            return None
        svc = self._services.get(svc_name)
        if svc is None:
            return None
        fn = getattr(svc, method_name, None)
        if fn is None or method_name.startswith("_"):
            return None
        if method_name.startswith("stream_"):
            async def stream_handler(request_bytes, context):
                kwargs = _de(request_bytes)
                async for item in fn(**kwargs):
                    yield _ser(item)

            return grpc.unary_stream_rpc_method_handler(
                stream_handler, request_deserializer=None,
                response_serializer=None)

        async def unary_handler(request_bytes, context):
            kwargs = _de(request_bytes)
            try:
                result = fn(**kwargs)
                if inspect.isawaitable(result):
                    result = await result
                return _ser({"ok": True, "result": result})
            except Exception as e:  # noqa: BLE001
                import traceback

                return _ser({
                    "ok": False,
                    "error": e,
                    "traceback": traceback.format_exc(),
                })

        return grpc.unary_unary_rpc_method_handler(
            unary_handler, request_deserializer=None,
            response_serializer=None)


class RpcServer:
    """grpc.aio server hosting named services on one port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._services: Dict[str, Any] = {}
        self._server: Optional[grpc.aio.Server] = None

    def add_service(self, name: str, service: Any) -> None:
        self._services[name] = service

    async def start(self) -> int:
        self._server = grpc.aio.server(options=GRPC_OPTIONS)
        self._server.add_generic_rpc_handlers(
            (_GenericHandler(self._services),))
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        if self.port == 0:
            raise RpcError(f"could not bind {self.host}")
        await self._server.start()
        return self.port

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            await self._server.stop(grace)


class AsyncRpcClient:
    """Channel to one peer; call services by name from async code."""

    def __init__(self, address: str):
        self.address = address
        self._channel = grpc.aio.insecure_channel(address,
                                                  options=GRPC_OPTIONS)
        self._callables: Dict[str, Any] = {}

    def _unary(self, path: str):
        rpc = self._callables.get(path)
        if rpc is None:
            rpc = self._channel.unary_unary(
                path, request_serializer=None, response_deserializer=None)
            self._callables[path] = rpc
        return rpc

    async def call(self, service: str, method: str,
                   timeout: Optional[float] = None, **kwargs) -> Any:
        rpc = self._unary(f"/raytpu.{service}/{method}")
        try:
            reply_bytes = await rpc(_ser(kwargs), timeout=timeout)
        except grpc.aio.AioRpcError as e:
            raise RpcError(
                f"RPC {service}.{method} to {self.address} failed: "
                f"{e.code().name} {e.details()}") from e
        reply = _de(reply_bytes)
        if not reply["ok"]:
            raise reply["error"]
        return reply["result"]

    def stream(self, service: str, method: str,
               timeout: Optional[float] = None, **kwargs):
        rpc = self._channel.unary_stream(
            f"/raytpu.{service}/{method}",
            request_serializer=None, response_deserializer=None)
        call = rpc(_ser(kwargs), timeout=timeout)

        async def gen():
            try:
                async for item_bytes in call:
                    yield _de(item_bytes)
            except grpc.aio.AioRpcError as e:
                raise RpcError(
                    f"stream {service}.{method} to {self.address} failed: "
                    f"{e.code().name} {e.details()}") from e

        return gen()

    async def close(self) -> None:
        await self._channel.close()


class EventLoopThread:
    """A dedicated asyncio loop on a background thread.

    Synchronous frontends (the user's driver thread, worker task threads)
    submit coroutines here; all gRPC aio machinery lives on this loop. The
    analogue of the instrumented asio event loop each reference process runs
    (ref: src/ray/common/asio/).
    """

    def __init__(self, name: str = "rpc-loop"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self._started.set()
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run coroutine on the loop, blocking the calling thread."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def submit(self, coro):
        """Fire-and-forget (returns concurrent Future)."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        def _shutdown():
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
            self.loop.stop()

        self.loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=2)


class SyncRpcClient:
    """Blocking facade over AsyncRpcClient via an EventLoopThread."""

    def __init__(self, address: str, loop_thread: EventLoopThread):
        self._loop = loop_thread
        self._client: Optional[AsyncRpcClient] = None
        self.address = address

    def _ensure(self) -> AsyncRpcClient:
        if self._client is None:
            async def mk():
                return AsyncRpcClient(self.address)

            self._client = self._loop.run(mk())
        return self._client

    def call(self, service: str, method: str,
             timeout: Optional[float] = None, **kwargs) -> Any:
        client = self._ensure()
        return self._loop.run(
            client.call(service, method, timeout=timeout, **kwargs),
            timeout=None if timeout is None else timeout + 5)

    def close(self):
        if self._client is not None:
            self._loop.run(self._client.close())
            self._client = None
