"""RPC plumbing: pickle-codec services over a length-prefixed TCP framing.

Role parity with the reference RPC framework (ref: src/ray/rpc/
grpc_server.h:85, grpc_client.h:92, client_call.h:188 — completion-queue
wrappers around generated stubs). Services are plain Python objects whose
public methods become unary RPCs; `stream_`-prefixed async generators
become server-streaming RPCs (chunked object transfer, pub/sub
long-polls).

The transport is a hand-rolled asyncio protocol, NOT grpc-python: the
reference's gRPC core is C++ with completion queues (~µs overhead), but
grpc-python's aio stack costs ~600µs per unary call on loopback — 14x
the cost of a length-prefixed frame over a plain asyncio stream (measured
in this environment: 657µs vs 47µs round-trip). Since every control-plane
hop (lease, push, heartbeat, directory update) rides this layer, the
framing IS the scheduler latency floor. Wire format:

    frame  := u32 length | u8 version | u8 type | u64 req_id | payload
    payload:= u8 codec | body    (codec 0 = pickle, 1 = typed; wire.py)
    types:    REQ, RES, STREAM_REQ, STREAM_ITEM, STREAM_END, CANCEL

The version byte is the schema seam the reference gets from proto3
(ref: src/ray/protobuf/core_worker.proto:425): a peer from a different
protocol generation receives a clear "protocol version mismatch" error
instead of a deserialize crash. The codec byte keeps pickle for
Python<->Python payloads while C++ peers speak the typed codec
(wire.py); the server always answers in the codec the request used.

Cancellation parity with gRPC deadlines: a client timeout sends CANCEL
(async) or drops the connection (sync), and the server cancels the
in-flight handler task — handlers relying on asyncio.CancelledError
semantics (lease grant shielding, runtime-env builds) behave identically.
"""
from __future__ import annotations

import asyncio
import inspect
import os
import pickle
import random
import socket
import struct
import threading
import time as _time
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu.core.distributed.wire import (
    CODEC_PICKLE,
    CODEC_RAW,
    CODEC_TYPED,
    PROTOCOL_VERSION,
    Raw,
    raw_dumps,
    raw_loads,
    scan_raw,
    typed_dumps,
    typed_loads,
    typed_safe,
)

MAX_FRAME = 512 * 1024 * 1024
# length (of version+type+id+payload), version, type, id
_HEADER = struct.Struct("<IBBQ")
_POST_LEN = 10  # bytes counted by `length` before the payload


REQ = 1
RES = 2
STREAM_REQ = 3
STREAM_ITEM = 4
STREAM_END = 5
CANCEL = 6


# ---------------------------------------------------------------------------
# Transport instrumentation (ref: the reference's per-method gRPC stats +
# instrumented asio event loops, src/ray/common/asio/instrumented_io_
# context.h). Per-service/method histograms for queue-wait and handler
# latency, inflight gauges, and bytes counters on the server and both
# clients — the framing IS the scheduler latency floor, so this is where
# control-plane regressions become visible. RAY_TPU_METRICS_RPC_ENABLED=0
# is the kill switch (the bench overhead probe flips it).
# ---------------------------------------------------------------------------

_rpc_metrics_singleton: Optional[dict] = None


def rpc_metrics() -> dict:
    """Process-wide transport metrics, created lazily (registry adoption
    makes repeat creation in in-proc harnesses safe)."""
    global _rpc_metrics_singleton
    if _rpc_metrics_singleton is None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _rpc_metrics_singleton = {
            "handler": Histogram(
                "raytpu_rpc_handler_seconds",
                "Server-side handler execution latency",
                tag_keys=("service", "method")),
            "queue_wait": Histogram(
                "raytpu_rpc_queue_wait_seconds",
                "Frame-decoded to handler-start queueing delay on the "
                "server event loop", tag_keys=("service", "method")),
            "client": Histogram(
                "raytpu_rpc_client_seconds",
                "Client-observed RPC round-trip latency",
                tag_keys=("service", "method")),
            "inflight": Gauge(
                "raytpu_rpc_inflight",
                "RPCs currently in flight", tag_keys=("side",)),
            "bytes": Counter(
                "raytpu_rpc_bytes_total",
                "Frame bytes moved over the RPC transport",
                tag_keys=("side", "direction")),
            "loop_lag": Histogram(
                "raytpu_event_loop_lag_seconds",
                "Event-loop scheduling lag (sleep-overshoot probe)",
                tag_keys=("loop",)),
        }
    return _rpc_metrics_singleton


def _instrumentation_enabled() -> bool:
    from ray_tpu.core.config import get_config

    return get_config().metrics_rpc_enabled


# ---------------------------------------------------------------------------
# Caller identity (GCS load attribution)
# ---------------------------------------------------------------------------
#
# Every process declares WHO it is once (node id + component:
# syncer / serve-gauges / task-events / scheduler / client); both
# clients then ride a reserved `_caller` kwarg inside the existing
# (service, method, kwargs) request tuple — zero wire-format change, no
# protocol bump. The server pops it before handler dispatch (user
# handlers never see it) and, when an attribution sink is installed
# (the GCS), accounts request/bytes/handler-time per (service,
# component). Call sites that act as a DIFFERENT component than their
# process default (the daemon's syncer push vs its scheduler RPCs)
# pass an explicit `_caller=(node_id, component)` kwarg which wins.

_caller_identity: Optional[Tuple[str, str]] = None


def set_caller_identity(node_id: str, component: str) -> None:
    """Declare this process's default caller identity for GCS load
    attribution. Applied to every subsequent RPC from this process
    unless the call site passes an explicit ``_caller=`` kwarg."""
    global _caller_identity
    _caller_identity = (node_id, component)


def get_caller_identity() -> Optional[Tuple[str, str]]:
    return _caller_identity


def _attribution_enabled() -> bool:
    from ray_tpu.core.config import get_config

    return get_config().gcs_attribution_enabled


def _inject_caller(kwargs: dict) -> None:
    if _caller_identity is not None and "_caller" not in kwargs \
            and _attribution_enabled():
        kwargs["_caller"] = _caller_identity


# Precomputed sample KEYS for the per-frame/per-call fast paths
# (metrics.*_key): the transport observes ~10 samples per RPC round
# trip, and building + sorting a tags dict per observation was a
# measurable slice of many_tasks throughput on a single-core host.
def _k(**tags) -> tuple:
    return tuple(sorted(tags.items()))


_K_SRV_IN = _k(side="server", direction="in")
_K_SRV_OUT = _k(side="server", direction="out")
_K_CLI_IN = _k(side="client", direction="in")
_K_CLI_OUT = _k(side="client", direction="out")
_K_SRV = _k(side="server")
_K_CLI = _k(side="client")
# (service, method) -> precomputed key, shared process-wide (the
# handler/queue-wait/client histograms share one tag shape).
_method_keys: Dict[Tuple[str, str], tuple] = {}


def _key_for(service: str, method: str) -> tuple:
    key = _method_keys.get((service, method))
    if key is None:
        key = _method_keys[(service, method)] = _k(service=service,
                                                   method=method)
    return key


def _payload_nbytes(payload) -> int:
    if isinstance(payload, list):
        return sum(len(p) for p in payload) + _HEADER.size
    return len(payload) + _HEADER.size


def _ser(obj: Any, codec: int = CODEC_PICKLE, safe: bool = False):
    """Codec-tagged payload. Pickle (the Python<->Python default) tries
    plain pickle first (RPC messages are dicts of primitives/bytes),
    cloudpickle as the fallback — ~3-5x faster on the hot path. Under
    the typed codec, `safe=True` (server REPLIES) projects exceptions
    and foreign objects onto the cross-language model via
    wire.typed_safe; REQUESTS stay strict so an out-of-model argument
    raises clearly instead of silently arriving as its repr string.

    A message carrying a wire.Raw marker (bulk chunk payloads) encodes
    as a RAW frame regardless of the requested codec and returns a LIST
    of buffers — typed header + the caller's body buffer untouched —
    for the transport to writev. Everything else returns bytes."""
    raw = scan_raw(obj)
    if raw is not None:
        header, body = raw_dumps(obj)
        return [b"\x02" + header, body]
    if codec == CODEC_TYPED:
        return b"\x01" + typed_dumps(typed_safe(obj) if safe else obj)
    try:
        return b"\x00" + pickle.dumps(obj, protocol=5)
    except Exception:  # noqa: BLE001 — closures, local classes, ...
        return b"\x00" + cloudpickle.dumps(obj, protocol=5)


def _de_codec(data: bytes) -> Tuple[Any, int]:
    if not data:
        raise RpcError("empty RPC payload")
    codec = data[0]
    view = memoryview(data)[1:]  # zero-copy past the codec byte
    if codec == CODEC_PICKLE:
        return pickle.loads(view), CODEC_PICKLE
    if codec == CODEC_TYPED:
        try:
            return typed_loads(view), CODEC_TYPED
        except Exception as e:  # noqa: BLE001 — corrupt payload must
            # surface as RpcError so client read loops classify it as
            # a transport fault, not an unhandled crash.
            raise RpcError(f"corrupt typed payload: {e}") from e
    if codec == CODEC_RAW:
        try:
            # The raw body arrives as a memoryview of `data`: the frame
            # bytes stay alive for exactly as long as the handler keeps
            # the view, and the chunk is never copied on the way in.
            return raw_loads(view), CODEC_RAW
        except Exception as e:  # noqa: BLE001
            raise RpcError(f"corrupt raw frame: {e}") from e
    raise RpcError(f"unknown payload codec {codec}")


def _de(data: bytes) -> Any:
    return _de_codec(data)[0]


class RpcError(Exception):
    pass


# ---------------------------------------------------------------------------
# Schedule-perturbation harness (race detection for the control plane)
# ---------------------------------------------------------------------------
#
# The reference catches ordering bugs in its C++ control plane with
# TSAN + randomized test schedules; our control plane is asyncio, where
# the realistic race surface is MESSAGE TIMING — actor seqnos, lease
# time-slicing, pubsub and pull-manager ordering all depend on when
# frames land relative to each other. With RAY_TPU_SCHED_FUZZ_MAX_MS
# set, every frame send sleeps a seeded pseudo-random delay first,
# perturbing cross-process interleavings the way a loaded host does —
# but reproducibly (RAY_TPU_SCHED_FUZZ_SEED, xor'd with the pid so each
# process gets a distinct stream). Child daemons inherit the env, so
# one setting fuzzes the whole cluster. Anything that breaks under it
# is a latent race, not a harness artifact: networks already reorder.

_fuzz_rng: Optional[random.Random] = None
_fuzz_seed: Optional[str] = None


def _sched_fuzz_delay() -> float:
    # lint: allow-knob -- fuzz harness reads env per call so seed sweeps work mid-process
    max_ms = os.environ.get("RAY_TPU_SCHED_FUZZ_MAX_MS")
    if not max_ms:
        return 0.0
    global _fuzz_rng, _fuzz_seed
    # lint: allow-knob -- fuzz harness reads env per call so seed sweeps work mid-process
    seed_s = os.environ.get("RAY_TPU_SCHED_FUZZ_SEED", "0")
    if _fuzz_rng is None or seed_s != _fuzz_seed:
        # Re-seed when the env seed changes mid-process (a test sweep
        # over seeds in one driver) — reproducibility demands the
        # driver replay the same stream as a standalone run.
        _fuzz_seed = seed_s
        _fuzz_rng = random.Random(int(seed_s) ^ os.getpid())
    return _fuzz_rng.random() * float(max_ms) / 1000.0


def _as_exception(err: Any) -> Exception:
    """Error field of a reply: a real exception under the pickle codec,
    a 'Type: message' string under the typed codec."""
    return err if isinstance(err, Exception) else RpcError(str(err))


class ProtocolVersionError(RpcError):
    """Peer speaks a different protocol generation."""

    def __init__(self, peer_version: int, req_id: int = 0):
        self.peer_version = peer_version
        self.req_id = req_id
        super().__init__(
            f"protocol version mismatch: peer sent v{peer_version}, "
            f"this node speaks v{PROTOCOL_VERSION}")


def _frame(ftype: int, req_id: int, payload: bytes) -> bytes:
    return _HEADER.pack(_POST_LEN + len(payload), PROTOCOL_VERSION,
                        ftype, req_id) + payload


def _frame_parts(ftype: int, req_id: int, parts: list) -> list:
    """Writev-style framing: header + payload buffers as separate
    segments, so a bulk body (a shm memoryview) reaches the socket
    without being concatenated into a fresh bytes object."""
    total = sum(len(p) for p in parts)
    return [_HEADER.pack(_POST_LEN + total, PROTOCOL_VERSION, ftype,
                         req_id)] + parts


async def _read_frame(reader: asyncio.StreamReader
                      ) -> Tuple[int, int, bytes]:
    head = await reader.readexactly(_HEADER.size)
    length, version, ftype, req_id = _HEADER.unpack(head)
    if length < _POST_LEN or length > MAX_FRAME:
        # < _POST_LEN would make readexactly() below receive a negative
        # count; either way the stream is garbage and must be dropped.
        raise RpcError(f"malformed frame length {length}")
    payload = await reader.readexactly(length - _POST_LEN)
    if version != PROTOCOL_VERSION:
        # Frame fully consumed, so the caller may answer before closing.
        raise ProtocolVersionError(version, req_id)
    return ftype, req_id, payload


class RpcServer:
    """Asyncio TCP server hosting named services on one port."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._services: Dict[str, Any] = {}
        self._server: Optional[asyncio.AbstractServer] = None
        self._conn_tasks: set = set()
        self._writers: set = set()
        self._metrics = rpc_metrics() if _instrumentation_enabled() \
            else None
        # GCS load attribution: when installed (GcsServer only), called
        # as sink((service, method, caller, in_nbytes), wall_s, kwargs,
        # stream=...) after every handler — caller is the popped
        # `_caller` identity tuple or None; stream=True means wall_s is
        # a stream's open lifetime, not loop occupancy. Must never
        # raise into the dispatch path.
        self.attribution_sink: Optional[Any] = None

    def add_service(self, name: str, service: Any) -> None:
        self._services[name] = service

    async def start(self) -> int:
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port, limit=MAX_FRAME)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    async def stop(self, grace: float = 0.5) -> None:
        if self._server is not None:
            self._server.close()
        for t in list(self._conn_tasks):
            t.cancel()
        # Abort live connections: on Python 3.12+ Server.wait_closed()
        # blocks until every connection handler returns, and persistent
        # clients never hang up on their own.
        for w in list(self._writers):
            try:
                w.transport.abort()
            except Exception:  # noqa: BLE001
                pass
        if self._server is not None:
            try:
                await asyncio.wait_for(self._server.wait_closed(), grace)
            except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                pass

    # -- per-connection serving ----------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        sock = writer.get_extra_info("socket")
        if sock is not None:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._writers.add(writer)
        wlock = asyncio.Lock()
        inflight: Dict[int, asyncio.Task] = {}
        metrics = self._metrics

        async def send(ftype: int, req_id: int, obj: Any,
                       codec: int = CODEC_PICKLE) -> None:
            try:
                payload = _ser(obj, codec, safe=True)
            except Exception as e:  # noqa: BLE001
                payload = _ser({"ok": False,
                                "error": RpcError(f"unpicklable: {e!r}")
                                if codec == CODEC_PICKLE
                                else f"unencodable reply: {e!r}"}, codec)
            d = _sched_fuzz_delay()
            if d:
                await asyncio.sleep(d)
            if metrics is not None:
                metrics["bytes"].inc_key(
                    _K_SRV_OUT, _payload_nbytes(payload))
            async with wlock:
                if isinstance(payload, list):
                    # Raw frame: hand each segment to the transport
                    # separately — the bulk body goes down as the
                    # handler's memoryview, never re-joined in Python.
                    for part in _frame_parts(ftype, req_id, payload):
                        writer.write(part)
                else:
                    writer.write(_frame(ftype, req_id, payload))
                await writer.drain()

        async def run_unary(req_id: int, fn, kwargs: dict, codec: int,
                            mkey: Optional[tuple] = None,
                            t_recv: float = 0.0,
                            attr: Optional[tuple] = None) -> None:
            if metrics is not None or attr is not None:
                now = _time.perf_counter()
            if metrics is not None:
                metrics["queue_wait"].observe_key(
                    mkey, max(0.0, now - t_recv))
                metrics["inflight"].inc_key(_K_SRV)
            try:
                result = fn(**kwargs)
                if inspect.isawaitable(result):
                    result = await result
                reply = {"ok": True, "result": result}
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                import traceback

                reply = {"ok": False, "error": e,
                         "traceback": traceback.format_exc()}
            finally:
                inflight.pop(req_id, None)
                if metrics is not None:
                    metrics["inflight"].inc_key(_K_SRV, -1)
                    metrics["handler"].observe_key(
                        mkey, _time.perf_counter() - now)
                if attr is not None:
                    sink = self.attribution_sink
                    if sink is not None:
                        try:
                            sink(attr, _time.perf_counter() - now, kwargs)
                        except Exception:  # noqa: BLE001
                            pass
            try:
                await send(RES, req_id, reply, codec)
            except (ConnectionError, OSError):
                pass  # client hung up mid-reply; nothing to tell it

        async def run_stream(req_id: int, fn, kwargs: dict, codec: int,
                             mkey: Optional[tuple] = None,
                             t_recv: float = 0.0,
                             attr: Optional[tuple] = None) -> None:
            if metrics is not None or attr is not None:
                now = _time.perf_counter()
            if metrics is not None:
                metrics["queue_wait"].observe_key(
                    mkey, max(0.0, now - t_recv))
                metrics["inflight"].inc_key(_K_SRV)
            try:
                async for item in fn(**kwargs):
                    await send(STREAM_ITEM, req_id, item, codec)
                end: Any = {"ok": True}
            except asyncio.CancelledError:
                inflight.pop(req_id, None)
                raise
            except (ConnectionError, OSError):
                inflight.pop(req_id, None)
                return  # consumer hung up mid-stream
            except Exception as e:  # noqa: BLE001
                end = {"ok": False, "error": e}
            finally:
                inflight.pop(req_id, None)
                if metrics is not None:
                    metrics["inflight"].inc_key(_K_SRV, -1)
                    metrics["handler"].observe_key(
                        mkey, _time.perf_counter() - now)
                if attr is not None:
                    sink = self.attribution_sink
                    if sink is not None:
                        # A stream's wall lifetime is await-time (a
                        # subscription can stay open for hours), not
                        # loop occupancy: count the request and its
                        # bytes, but no handler seconds, and keep it
                        # out of the slow-handler audit.
                        try:
                            sink(attr, _time.perf_counter() - now,
                                 kwargs, stream=True)
                        except Exception:  # noqa: BLE001
                            pass
            try:
                await send(STREAM_END, req_id, end, codec)
            except (ConnectionError, OSError):
                pass

        try:
            while True:
                try:
                    ftype, req_id, payload = await _read_frame(reader)
                except ProtocolVersionError as e:
                    # Answer with a clear typed error (the one codec a
                    # foreign-generation peer most plausibly decodes),
                    # then drop the connection — never unpickle bytes
                    # from a different protocol generation.
                    try:
                        await send(RES, e.req_id,
                                   {"ok": False, "error": str(e)},
                                   CODEC_TYPED)
                    except (ConnectionError, OSError):
                        pass
                    return
                except (asyncio.IncompleteReadError, ConnectionError,
                        OSError, RpcError):
                    return
                if ftype == CANCEL:
                    task = inflight.pop(req_id, None)
                    if task is not None:
                        task.cancel()
                    continue
                if metrics is not None:
                    t_recv = _time.perf_counter()
                    metrics["bytes"].inc_key(
                        _K_SRV_IN, len(payload) + _HEADER.size)
                else:
                    t_recv = 0.0
                try:
                    (service, method, kwargs), codec = _de_codec(payload)
                except Exception:  # noqa: BLE001
                    continue
                # Reserved attribution kwarg: popped unconditionally so
                # handlers never see it, accounted only when a sink is
                # installed (the GCS).
                caller = kwargs.pop("_caller", None) \
                    if isinstance(kwargs, dict) else None
                svc = self._services.get(service)
                fn = (None if svc is None or method.startswith("_")
                      else getattr(svc, method, None))
                if fn is None:
                    await send(RES, req_id, {
                        "ok": False,
                        "error": RpcError(
                            f"no such RPC {service}.{method}")}, codec)
                    continue
                mkey = (_key_for(service, method)
                        if metrics is not None else None)
                attr = ((service, method, caller,
                         len(payload) + _HEADER.size)
                        if self.attribution_sink is not None else None)
                runner = (run_stream if ftype == STREAM_REQ else run_unary)
                task = asyncio.ensure_future(
                    runner(req_id, fn, kwargs, codec, mkey, t_recv, attr))
                inflight[req_id] = task
                self._conn_tasks.add(task)
                task.add_done_callback(self._conn_tasks.discard)
        finally:
            # Connection gone: cancel its in-flight handlers, mirroring
            # gRPC's deadline/disconnect cancellation.
            self._writers.discard(writer)
            for task in inflight.values():
                task.cancel()
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass


class AsyncRpcClient:
    """Multiplexed connection to one peer; call services from async code.

    All I/O happens on the event loop the first call runs on (one loop
    per process, the EventLoopThread)."""

    def __init__(self, address: str, codec: int = CODEC_PICKLE):
        self.address = address
        self.codec = codec
        self._metrics = rpc_metrics() if _instrumentation_enabled() \
            else None
        self._reader: Optional[asyncio.StreamReader] = None
        self._writer: Optional[asyncio.StreamWriter] = None
        self._wlock: Optional[asyncio.Lock] = None
        self._conn_lock: Optional[asyncio.Lock] = None
        self._pending: Dict[int, asyncio.Future] = {}
        self._streams: Dict[int, asyncio.Queue] = {}
        self._req_id = 0
        self._reader_task: Optional[asyncio.Task] = None
        self._closed = False

    async def _ensure_conn(self) -> None:
        if self._writer is not None and not self._writer.is_closing():
            return
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:
            if self._writer is not None and not self._writer.is_closing():
                return
            if self._closed:
                raise RpcError(f"client to {self.address} is closed")
            host, port = self.address.rsplit(":", 1)
            try:
                reader, writer = await asyncio.open_connection(
                    host, int(port), limit=MAX_FRAME)
            except OSError as e:
                raise RpcError(
                    f"connect to {self.address} failed: {e}") from e
            sock = writer.get_extra_info("socket")
            if sock is not None:
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._reader, self._writer = reader, writer
            self._wlock = asyncio.Lock()
            self._reader_task = asyncio.ensure_future(self._read_loop())

    async def _read_loop(self) -> None:
        reader = self._reader
        metrics = self._metrics
        try:
            while True:
                ftype, req_id, payload = await _read_frame(reader)
                if metrics is not None:
                    metrics["bytes"].inc_key(
                        _K_CLI_IN, len(payload) + _HEADER.size)
                if ftype == RES:
                    fut = self._pending.pop(req_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(_de(payload))
                elif ftype == STREAM_ITEM:
                    q = self._streams.get(req_id)
                    if q is not None:
                        q.put_nowait(("item", _de(payload)))
                elif ftype == STREAM_END:
                    q = self._streams.pop(req_id, None)
                    if q is not None:
                        q.put_nowait(("end", _de(payload)))
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                RpcError, asyncio.CancelledError) as e:
            if isinstance(e, asyncio.CancelledError):
                # Deliberate close(): cancel waiters instead of setting
                # exceptions nobody will retrieve.
                for fut in self._pending.values():
                    if not fut.done():
                        fut.cancel()
            else:
                err = RpcError(f"connection to {self.address} lost: {e!r}")
                for fut in self._pending.values():
                    if not fut.done():
                        fut.set_exception(err)
            self._pending.clear()
            err = RpcError(f"connection to {self.address} lost: {e!r}")
            for q in self._streams.values():
                q.put_nowait(("end", {"ok": False, "error": err}))
            self._streams.clear()
            if self._writer is not None:
                try:
                    self._writer.close()
                except Exception:  # noqa: BLE001
                    pass

    async def _send(self, ftype: int, req_id: int, obj: Any) -> None:
        d = _sched_fuzz_delay()
        if d:
            await asyncio.sleep(d)
        payload = _ser(obj, self.codec)
        if self._metrics is not None:
            self._metrics["bytes"].inc_key(
                _K_CLI_OUT, _payload_nbytes(payload))
        async with self._wlock:
            if isinstance(payload, list):
                for part in _frame_parts(ftype, req_id, payload):
                    self._writer.write(part)
            else:
                self._writer.write(_frame(ftype, req_id, payload))
            await self._writer.drain()

    async def call(self, service: str, method: str,
                   timeout: Optional[float] = None, **kwargs) -> Any:
        if self._metrics is None:
            return await self._call(service, method, timeout, **kwargs)
        t0 = _time.perf_counter()
        self._metrics["inflight"].inc_key(_K_CLI)
        try:
            return await self._call(service, method, timeout, **kwargs)
        finally:
            self._metrics["inflight"].inc_key(_K_CLI, -1)
            self._metrics["client"].observe_key(
                _key_for(service, method), _time.perf_counter() - t0)

    async def _call(self, service: str, method: str,
                    timeout: Optional[float] = None, **kwargs) -> Any:
        _inject_caller(kwargs)
        await self._ensure_conn()
        self._req_id += 1
        req_id = self._req_id
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        try:
            await self._send(REQ, req_id, (service, method, kwargs))
        except (ConnectionError, OSError) as e:
            self._pending.pop(req_id, None)
            raise RpcError(
                f"RPC {service}.{method} to {self.address} failed: "
                f"{e!r}") from e
        except Exception:  # encode error (e.g. WireError): not sent
            self._pending.pop(req_id, None)
            raise
        try:
            if timeout is not None:
                reply = await asyncio.wait_for(fut, timeout)
            else:
                reply = await fut
        except (TimeoutError, asyncio.TimeoutError):
            self._pending.pop(req_id, None)
            # Parity with gRPC deadlines: cancel the server-side handler.
            try:
                await self._send(CANCEL, req_id, None)
            except Exception:  # noqa: BLE001
                pass
            raise RpcError(
                f"RPC {service}.{method} to {self.address} failed: "
                f"DEADLINE_EXCEEDED after {timeout}s") from None
        except asyncio.CancelledError:
            self._pending.pop(req_id, None)
            try:
                await self._send(CANCEL, req_id, None)
            except Exception:  # noqa: BLE001
                pass
            raise
        if not reply["ok"]:
            raise _as_exception(reply.get("error"))
        return reply["result"]

    def stream(self, service: str, method: str,
               timeout: Optional[float] = None, **kwargs):
        async def gen():
            _inject_caller(kwargs)
            await self._ensure_conn()
            self._req_id += 1
            req_id = self._req_id
            q: asyncio.Queue = asyncio.Queue()
            self._streams[req_id] = q
            await self._send(STREAM_REQ, req_id, (service, method, kwargs))
            try:
                while True:
                    if timeout is not None:
                        kind, value = await asyncio.wait_for(q.get(),
                                                             timeout)
                    else:
                        kind, value = await q.get()
                    if kind == "item":
                        yield value
                        continue
                    if not value.get("ok"):
                        raise _as_exception(value.get("error"))
                    return
            except (TimeoutError, asyncio.TimeoutError):
                raise RpcError(
                    f"stream {service}.{method} to {self.address} "
                    f"failed: DEADLINE_EXCEEDED") from None
            finally:
                if self._streams.pop(req_id, None) is not None:
                    # Early exit: stop the server-side generator.
                    try:
                        await self._send(CANCEL, req_id, None)
                    except Exception:  # noqa: BLE001
                        pass

        return gen()

    async def close(self) -> None:
        """Clean shutdown: cancel AND await the read loop (a cancelled-
        but-never-awaited task produces 'Task was destroyed but it is
        pending!' at interpreter exit), cancel pending call futures, and
        close the transport."""
        self._closed = True
        task, self._reader_task = self._reader_task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        for fut in self._pending.values():
            if not fut.done():
                fut.cancel()
        self._pending.clear()
        writer, self._writer = self._writer, None
        if writer is not None:
            try:
                writer.close()
                await asyncio.wait_for(writer.wait_closed(), 0.5)
            except (Exception, asyncio.TimeoutError):  # noqa: BLE001
                pass


class EventLoopThread:
    """A dedicated asyncio loop on a background thread.

    Synchronous frontends (the user's driver thread, worker task threads)
    submit coroutines here; all async RPC machinery lives on this loop.
    The analogue of the instrumented asio event loop each reference
    process runs (ref: src/ray/common/asio/)."""

    # Dispatch-heavy processes (driver submit thread vs RPC loop, worker
    # executor vs RPC loop) ping-pong the GIL; CPython's default 5ms
    # switch interval lets one side hold it for entire scheduling
    # quanta, serializing the pipeline (measured: n:n actor submission
    # 2.5k/s at 5ms vs 5k/s at 0.5ms). Applied only when the process is
    # still on CPython's factory default — an embedding application that
    # chose its own interval keeps it.
    SWITCH_INTERVAL_S = 0.0005
    _DEFAULT_SWITCH_INTERVAL_S = 0.005

    def __init__(self, name: str = "rpc-loop"):
        import sys as _sys

        if _sys.getswitchinterval() == self._DEFAULT_SWITCH_INTERVAL_S:
            _sys.setswitchinterval(self.SWITCH_INTERVAL_S)
        self.loop = asyncio.new_event_loop()
        # Strong roots for submitted background tasks: asyncio holds only
        # WEAK references to tasks, so a fire-and-forget coroutine whose
        # awaited future is reachable only through its own frame (task →
        # frame → client → queue → future → task) is one unreferenced
        # cycle the GC will happily collect MID-FLIGHT — the coroutine
        # silently dies with GeneratorExit (observed: the driver's log
        # subscriber vanished at the first gc pass after init).
        self._bg_tasks: set = set()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()
        self._maybe_start_lag_probe(name)

    def _maybe_start_lag_probe(self, name: str) -> None:
        """Event-loop lag probe (ref: instrumented_io_context.h): a
        periodic sleep measures its own scheduling overshoot — the
        direct signal that a handler is hogging the loop (the exact
        failure mode the reference's asio stats catch). Off when RPC
        instrumentation is off or RAY_TPU_METRICS_LOOP_PROBE_MS=0."""
        from ray_tpu.core.config import get_config

        probe_ms = get_config().metrics_loop_probe_ms
        if not probe_ms or not _instrumentation_enabled():
            return

        async def probe() -> None:
            hist = rpc_metrics()["loop_lag"]
            tags = {"loop": name}
            interval = probe_ms / 1000.0
            while True:
                t0 = self.loop.time()
                await asyncio.sleep(interval)
                hist.observe(max(0.0, self.loop.time() - t0 - interval),
                             tags)

        self.submit(probe())

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self._started.set()
        self.loop.run_forever()

    def run(self, coro, timeout: Optional[float] = None):
        """Run coroutine on the loop, blocking the calling thread."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def submit(self, coro):
        """Fire-and-forget (returns concurrent Future). The task is
        rooted in self._bg_tasks until done — see __init__."""
        async def rooted():
            task = asyncio.current_task()
            self._bg_tasks.add(task)
            try:
                return await coro
            finally:
                self._bg_tasks.discard(task)

        return asyncio.run_coroutine_threadsafe(rooted(), self.loop)

    def stop(self):
        async def _drain():
            # Sweep REPEATEDLY: a cancelled task's cleanup can spawn new
            # tasks (e.g. a failure handler resubmitting work), and a
            # single sweep would leave those to die as destroyed-pending
            # tasks at interpreter exit.
            # Generous deadline: on a loaded single-CPU host a 2s sweep
            # budget expired mid-drain, leaving cancelled-but-unawaited
            # tasks to die as destroy-pending noise at interpreter exit.
            deadline = self.loop.time() + 6.0
            try:
                while True:
                    tasks = [t for t in asyncio.all_tasks(self.loop)
                             if t is not asyncio.current_task()]
                    if not tasks or self.loop.time() >= deadline:
                        break
                    for task in tasks:
                        task.cancel()
                    await asyncio.wait(tasks, timeout=0.5)
            finally:
                self.loop.stop()

        def _shutdown():
            asyncio.ensure_future(_drain())

        self.loop.call_soon_threadsafe(_shutdown)
        self._thread.join(timeout=4)


class _BlockingConn:
    """One blocking socket running one request at a time."""

    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self.sock = socket.create_connection((host, int(port)), timeout=30)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = bytearray()
        self.last_recv_nbytes = 0

    def stale(self) -> bool:
        """Has the peer closed this pooled socket (restarted server)?

        A non-blocking MSG_PEEK distinguishes 'peer sent FIN/RST while
        pooled' from 'healthy idle socket' WITHOUT consuming data —
        detecting staleness BEFORE the request is sent, so the caller
        never has to guess whether a failed request already executed."""
        try:
            self.sock.setblocking(False)
            try:
                data = self.sock.recv(1, socket.MSG_PEEK)
                return data == b""      # orderly FIN
            finally:
                self.sock.setblocking(True)
        except BlockingIOError:
            return False                # nothing to read: healthy idle
        except OSError:
            return True                 # RST or dead fd

    def send_request(self, req_id: int, payload,
                     timeout: Optional[float]) -> None:
        d = _sched_fuzz_delay()
        if d:
            _time.sleep(d)
        self.sock.settimeout(timeout)
        if isinstance(payload, list):
            # Raw frame: sendall per segment (writev-style, no join).
            for part in _frame_parts(REQ, req_id, payload):
                self.sock.sendall(part)
        else:
            self.sock.sendall(_frame(REQ, req_id, payload))

    def recv_reply(self, req_id: int) -> Any:
        while True:
            ftype, rid, body = self._recv_frame()
            if ftype == RES and rid == req_id:
                return _de(body)
            # Stale frame from an abandoned request on this socket —
            # cannot happen (a timed-out socket is discarded), but skip
            # defensively rather than corrupt the stream.

    def _recv_frame(self) -> Tuple[int, int, bytes]:
        need = _HEADER.size
        while len(self._buf) < need:
            chunk = self.sock.recv(256 * 1024)
            if not chunk:
                raise ConnectionError("peer closed")
            self._buf += chunk
        length, version, ftype, req_id = _HEADER.unpack_from(self._buf, 0)
        if length < _POST_LEN or length > MAX_FRAME:
            raise RpcError(f"malformed frame length {length}")
        total = _HEADER.size + length - _POST_LEN
        while len(self._buf) < total:
            chunk = self.sock.recv(1024 * 1024)
            if not chunk:
                raise ConnectionError("peer closed")
            self._buf += chunk
        payload = bytes(self._buf[_HEADER.size:total])
        del self._buf[:total]
        self.last_recv_nbytes = total
        if version != PROTOCOL_VERSION:
            raise ProtocolVersionError(version, req_id)
        return ftype, req_id, payload

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class SyncRpcClient:
    """Blocking client: a small pool of dedicated sockets, no event-loop
    hops. The async facade costs two cross-thread wakeups per call
    (~0.5ms); a blocking socket round-trips in ~50µs, and the control
    plane's sync callers (driver get/put, worker→GCS bookkeeping) sit on
    exactly that path."""

    MAX_POOL = 16

    def __init__(self, address: str, loop_thread: EventLoopThread = None,
                 codec: int = CODEC_PICKLE):
        self.address = address
        self.codec = codec
        self._loop = loop_thread        # kept for API compatibility
        self._metrics = rpc_metrics() if _instrumentation_enabled() \
            else None
        self._pool: list = []
        self._lock = threading.Lock()
        self._req_id = 0
        self._sem = threading.BoundedSemaphore(self.MAX_POOL)

    def call(self, service: str, method: str,
             timeout: Optional[float] = None, idempotent: bool = False,
             **kwargs) -> Any:
        if self._metrics is None:
            return self._call(service, method, timeout, idempotent,
                              **kwargs)
        t0 = _time.perf_counter()
        self._metrics["inflight"].inc_key(_K_CLI)
        try:
            return self._call(service, method, timeout, idempotent,
                              **kwargs)
        finally:
            self._metrics["inflight"].inc_key(_K_CLI, -1)
            self._metrics["client"].observe_key(
                _key_for(service, method), _time.perf_counter() - t0)

    def _call(self, service: str, method: str,
              timeout: Optional[float] = None, idempotent: bool = False,
              **kwargs) -> Any:
        """One blocking RPC.

        Retry semantics (at-most-once by default): stale pooled sockets
        are detected with a MSG_PEEK probe BEFORE the request is sent,
        and a send-phase failure retries on a fresh connection — in both
        cases the request provably never executed. A failure during the
        reply phase means the server may have already executed the
        handler, so it is NOT retried (gRPC's transparent reconnect has
        the same rule) — unless the caller declares the method
        `idempotent=True` (reads, status polls, overwriting KV puts).
        """
        _inject_caller(kwargs)
        payload = _ser((service, method, kwargs), self.codec)
        with self._lock:
            self._req_id += 1
            req_id = self._req_id

        def fresh_conn() -> _BlockingConn:
            try:
                return _BlockingConn(self.address)
            except OSError as e:
                raise RpcError(
                    f"connect to {self.address} failed: {e}") from e

        def rpc_error(e, phase: str) -> RpcError:
            return RpcError(
                f"RPC {service}.{method} to {self.address} failed "
                f"({phase}): {e!r}")

        self._sem.acquire()
        conn = None
        try:
            # Pull a pooled socket, discarding any the peer has closed.
            while conn is None:
                with self._lock:
                    if not self._pool:
                        break
                    conn = self._pool.pop()
                if conn.stale():
                    conn.close()
                    conn = None
            if conn is None:
                conn = fresh_conn()
            try:
                conn.send_request(req_id, payload, timeout)
            except (ConnectionError, OSError, socket.timeout) as e:
                # Request never fully reached the server (a partial
                # frame is dropped by the server's length check): safe
                # to retry once on a fresh connection.
                conn.close()
                conn = fresh_conn()
                try:
                    conn.send_request(req_id, payload, timeout)
                except (ConnectionError, OSError, socket.timeout) as e2:
                    conn.close()
                    raise rpc_error(e2, "send") from e2
            for attempt in (0, 1):
                try:
                    reply = conn.recv_reply(req_id)
                    break
                except socket.timeout:
                    # Mid-reply socket is unusable: drop it. The server
                    # sees the close and cancels the handler (deadline
                    # parity with gRPC).
                    conn.close()
                    conn = None
                    raise RpcError(
                        f"RPC {service}.{method} to {self.address} "
                        f"failed: DEADLINE_EXCEEDED after {timeout}s"
                    ) from None
                except (ConnectionError, OSError, RpcError) as e:
                    conn.close()
                    conn = None
                    if not idempotent or attempt:
                        raise rpc_error(e, "recv") from e
                    conn = fresh_conn()
                    try:
                        conn.send_request(req_id, payload, timeout)
                    except (ConnectionError, OSError,
                            socket.timeout) as e2:
                        conn.close()
                        conn = None
                        raise rpc_error(e2, "send") from e2
            if self._metrics is not None:
                self._metrics["bytes"].inc_key(
                    _K_CLI_OUT, _payload_nbytes(payload))
                self._metrics["bytes"].inc_key(
                    _K_CLI_IN, conn.last_recv_nbytes)
            with self._lock:
                if conn is not None and len(self._pool) < self.MAX_POOL:
                    self._pool.append(conn)
                    conn = None
            if conn is not None:
                conn.close()
                conn = None
        finally:
            if conn is not None:
                conn.close()
            self._sem.release()
        if not reply["ok"]:
            raise _as_exception(reply.get("error"))
        return reply["result"]

    def close(self):
        with self._lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()
