"""GCS: the cluster control plane.

Analogue of the reference GCS server (ref: src/ray/gcs/gcs_server/
gcs_server.cc:182 DoStart — node manager, resource manager, health check,
job manager, PG manager, actor manager, worker manager, task manager; storage
tables gcs_table_storage.h). One asyncio process hosting:

  NodeInfo         — node registry + heartbeats + health checks
  KV               — cluster KV store (also the function table)
  ActorManager     — actor scheduling, restarts, named actors
  ObjectDirectory  — object locations + distributed free
  PlacementGroups  — bundle reservation across nodes
  JobManager       — driver/job registry
  TaskEvents       — task event sink powering the state API
  Pubsub           — long-poll pub/sub (ref: src/ray/pubsub/)
  LogManager       — worker log hub: ring buffers + driver streaming

State lives in memory (the reference's default, ray_config_def.h:402
gcs_storage="memory"); a Redis-equivalent durable backend can be slotted in
at the _Store seam.
"""
from __future__ import annotations

import asyncio
import dataclasses
import logging
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.core.distributed import resources as rs
from ray_tpu.core.distributed.rpc import AsyncRpcClient, RpcServer
from ray_tpu.core.distributed.scheduler import (
    ClusterView,
    NodeView,
    pick_node,
    place_bundles,
)

logger = logging.getLogger(__name__)


class Pubsub:
    """Channelized pub/sub over server-streaming RPCs (ref: src/ray/pubsub/
    publisher.h — long-poll batched delivery)."""

    def __init__(self):
        self._subs: Dict[str, List[asyncio.Queue]] = defaultdict(list)

    def publish(self, channel: str, message: Any) -> int:
        for q in list(self._subs.get(channel, [])):
            q.put_nowait(message)
        return len(self._subs.get(channel, []))

    async def stream_subscribe(self, channel: str):
        q: asyncio.Queue = asyncio.Queue()
        self._subs[channel].append(q)
        try:
            while True:
                yield await q.get()
        finally:
            self._subs[channel].remove(q)


class KV:
    """Namespaced key-value store (ref: gcs InternalKV — used for the
    function table, runtime env URIs, cluster metadata). Durable when
    the GCS runs with a storage dir (the function table must survive a
    GCS restart or restarted actors cannot fetch their classes)."""

    def __init__(self, store=None):
        from ray_tpu.core.distributed.gcs_storage import NullStore

        self._store = store or NullStore()
        self._data: Dict[Tuple[str, bytes], bytes] = dict(
            self._store.all("kv"))
        self.flight: Optional["FlightRecorder"] = None  # set by GcsServer

    def put(self, namespace: str, key: bytes, value: bytes,
            overwrite: bool = True) -> bool:
        k = (namespace, key)
        if not overwrite and k in self._data:
            return False
        self._data[k] = value
        self._store.put("kv", k, value)
        if (self.flight is not None and namespace == "serve"
                and key.startswith(b"migrate:")):
            # Live KV-migration tickets (serve drain) transit this KV:
            # journal the publish leg so `ray-tpu events` shows the
            # drain's migration hops next to the drain itself.
            self.flight.record(
                "serve.kv_migrate",
                "migration ticket published: "
                + key[len(b"migrate:"):].decode("utf-8", "replace"),
                fields={"nbytes": len(value)})
        return True

    def get(self, namespace: str, key: bytes) -> Optional[bytes]:
        return self._data.get((namespace, key))

    def delete(self, namespace: str, key: bytes) -> bool:
        self._store.delete("kv", (namespace, key))
        return self._data.pop((namespace, key), None) is not None

    def keys(self, namespace: str, prefix: bytes = b"") -> List[bytes]:
        return [k for (ns, k) in self._data if ns == namespace
                and k.startswith(prefix)]


class NodeInfo:
    """Node registry + heartbeat-driven health checking (ref:
    gcs_node_manager.h:44, gcs_health_check_manager.h:39)."""

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs
        self.view = ClusterView()

    def register_node(self, node_id: str, address: str,
                      resources: Dict[str, float], store_dir: str,
                      labels: Optional[Dict[str, str]] = None) -> dict:
        prior = self.view.nodes.get(node_id)
        self.view.nodes[node_id] = NodeView(
            node_id=node_id, address=address, total=dict(resources),
            available=dict(resources), store_dir=store_dir,
            labels=labels or {})
        if prior is not None and not prior.alive:
            # An explicit resurrection, not silent flapping: the daemon
            # was told "stale node" and chose to re-register as a fresh
            # incarnation (its actors/objects were already failed over).
            logger.warning("dead node %s re-registered at %s", node_id[:8],
                           address)
            self._gcs.event_log.emit(
                "node", "WARNING",
                f"node {node_id[:8]} re-registered after being marked "
                f"dead", node_id=node_id, address=address)
            self._gcs.flight.record(
                "node.rejoin",
                f"node {node_id[:8]} re-registered after being marked "
                f"dead", node_id=node_id, severity="WARNING",
                fields={"address": address})
        else:
            logger.info("node %s registered at %s resources=%s",
                        node_id[:8], address, resources)
            self._gcs.event_log.emit("node", "INFO",
                                     f"node {node_id[:8]} registered",
                                     node_id=node_id, address=address)
            self._gcs.flight.record(
                "node.join", f"node {node_id[:8]} registered",
                node_id=node_id,
                fields={"address": address, "resources": dict(resources)})
        self._gcs.syncer.on_node_registered(node_id)
        self._gcs.pubsub.publish(
            "node", {"event": "added", "node_id": node_id,
                     "address": address, "resources": resources,
                     "store_dir": store_dir})
        return {"node_id": node_id}

    def heartbeat(self, node_id: str, available: Dict[str, float],
                  queued_demand: Optional[List[Dict[str, float]]] = None
                  ) -> dict:
        n = self.view.nodes.get(node_id)
        if n is None:
            return {"registered": False}  # ask the node to re-register
        if not n.alive:
            # Explicit stale-node verdict: updates from a node already
            # marked dead must not flap its entry back to life — the
            # daemon re-registers deliberately (a fresh incarnation) and
            # full-resyncs its state through the syncer.
            return {"registered": False, "stale": True,
                    "reason": f"node {node_id[:8]} is marked dead"}
        self.view.update(node_id, available, queued=queued_demand)
        self._gcs.syncer.on_node_heartbeat(node_id)
        return {"registered": True}

    def list_nodes(self) -> List[dict]:
        return [
            {
                "node_id": n.node_id,
                "address": n.address,
                "alive": n.alive,
                "total": n.total,
                "available": n.available,
                "store_dir": n.store_dir,
                "labels": n.labels,
            }
            for n in self.view.nodes.values()
        ]

    def drain_node(self, node_id: str) -> dict:
        return self.mark_dead(node_id, reason="drained")

    def mark_dead(self, node_id: str, reason: str = "health check failed"
                  ) -> dict:
        n = self.view.nodes.get(node_id)
        if n is None or not n.alive:
            return {"ok": False}
        n.alive = False
        logger.warning("node %s marked dead: %s", node_id[:8], reason)
        self._gcs.event_log.emit("node", "WARNING",
                                 f"node {node_id[:8]} dead: {reason}",
                                 node_id=node_id, reason=reason)
        self._gcs.flight.record(
            "node.drain" if reason == "drained" else "node.death",
            f"node {node_id[:8]} dead: {reason}", node_id=node_id,
            severity="WARNING", fields={"reason": reason})
        self._gcs.syncer.on_node_dead(node_id)
        self._gcs.pubsub.publish(
            "node", {"event": "dead", "node_id": node_id, "reason": reason})
        self._gcs.actors.on_node_dead(node_id)
        self._gcs.objects.on_node_dead(node_id)
        self._gcs.placement_groups.on_node_dead(node_id)
        self._gcs.metrics.on_node_dead(node_id)
        return {"ok": True}

    async def health_check_loop(self):
        cfg = get_config()
        period = cfg.health_check_period_ms / 1000
        threshold = cfg.health_check_failure_threshold
        await asyncio.sleep(cfg.health_check_initial_delay_ms / 1000)
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for n in list(self.view.nodes.values()):
                if n.alive and now - n.last_heartbeat > period * threshold:
                    self.mark_dead(n.node_id)


ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


@dataclasses.dataclass
class ActorRecord:
    actor_id: str
    cls_blob_key: bytes            # function-table key for the pickled class
    cls_name: str
    args_blob: bytes               # serialized (args, kwargs)
    demand: Dict[str, float]
    max_restarts: int
    restarts_used: int = 0
    name: Optional[str] = None
    namespace: str = "default"
    detached: bool = False
    owner_job: str = ""
    state: str = ACTOR_PENDING
    node_id: str = ""
    worker_address: str = ""
    death_reason: str = ""
    max_concurrency: int = 1
    placement: Optional[Tuple[str, int]] = None  # (pg_id, bundle_idx)
    runtime_env: Optional[dict] = None           # normalized spec
    # name -> max concurrent executions (ref: concurrency groups,
    # concurrency_group_manager.h)
    concurrency_groups: Dict[str, int] = dataclasses.field(
        default_factory=dict)


class ActorManager:
    """Actor scheduling + fault handling (ref: gcs_actor_manager.h:281,
    gcs_actor_scheduler.h). Creation flow: pick node → ask its daemon to
    start a dedicated worker → push the creation task → publish address."""

    def __init__(self, gcs: "GcsServer", store=None):
        from ray_tpu.core.distributed.gcs_storage import NullStore

        self._gcs = gcs
        self._store = store or NullStore()
        self.actors: Dict[str, ActorRecord] = {}
        self.named: Dict[Tuple[str, str], str] = {}
        self._pending: asyncio.Queue = asyncio.Queue()
        # wait_actor long-poll parkers, woken by _publish: actor_id ->
        # futures of callers waiting for the NEXT state transition.
        self._state_waiters: Dict[str, List[asyncio.Future]] = {}
        # Recovery (ref: GcsActorManager::Initialize reloading from
        # storage): reload records; queued/restarting actors reschedule,
        # ALIVE ones are revalidated once daemons re-register.
        for rec_dict in self._store.all("actor").values():
            rec = ActorRecord(**rec_dict)
            self.actors[rec.actor_id] = rec
            if rec.name and rec.state != ACTOR_DEAD:
                self.named[(rec.namespace, rec.name)] = rec.actor_id

    def requeue_loaded(self) -> None:
        """Called once the event loop runs: resume scheduling of loaded
        non-terminal actors and validate loaded ALIVE ones."""
        for rec in self.actors.values():
            if rec.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                self._pending.put_nowait(rec.actor_id)
        alive = [r.actor_id for r in self.actors.values()
                 if r.state == ACTOR_ALIVE]
        if alive:
            asyncio.ensure_future(self._validate_loaded(alive))

    async def _validate_loaded(self, actor_ids: List[str]) -> None:
        # Let daemons re-register first (their workers may be fine).
        await asyncio.sleep(get_config().health_check_period_ms / 1000 * 2)
        for aid in actor_ids:
            rec = self.actors.get(aid)
            if rec is None or rec.state != ACTOR_ALIVE:
                continue
            ok = False
            try:
                client = AsyncRpcClient(rec.worker_address)
                try:
                    reply = await client.call("Worker", "ping", timeout=5)
                    ok = reply.get("actor_id") == rec.actor_id
                finally:
                    await client.close()
            except Exception:  # noqa: BLE001
                ok = False
            if not ok:
                self._handle_failure(rec, "worker lost while GCS was down")

    def _persist(self, rec: ActorRecord) -> None:
        self._store.put("actor", rec.actor_id, dataclasses.asdict(rec))

    # -- RPC surface ----------------------------------------------------
    async def create_actor(self, record: dict) -> dict:
        rec = ActorRecord(**record)
        if rec.name:
            key = (rec.namespace, rec.name)
            if key in self.named:
                raise ValueError(
                    f"Actor name '{rec.name}' already taken in namespace "
                    f"'{rec.namespace}'")
            self.named[key] = rec.actor_id
        self.actors[rec.actor_id] = rec
        self._persist(rec)
        await self._pending.put(rec.actor_id)
        return {"actor_id": rec.actor_id}

    def get_actor(self, actor_id: Optional[str] = None,
                  name: Optional[str] = None,
                  namespace: str = "default") -> Optional[dict]:
        if actor_id is None and name is not None:
            actor_id = self.named.get((namespace, name))
        rec = self.actors.get(actor_id) if actor_id else None
        if rec is None:
            return None
        return {
            "actor_id": rec.actor_id, "state": rec.state,
            "worker_address": rec.worker_address, "node_id": rec.node_id,
            "cls_name": rec.cls_name, "name": rec.name,
            "death_reason": rec.death_reason,
            "max_concurrency": rec.max_concurrency,
        }

    def list_actors(self) -> List[dict]:
        return [self.get_actor(a) for a in self.actors]

    async def kill_actor(self, actor_id: str, no_restart: bool = True) -> dict:
        rec = self.actors.get(actor_id)
        if rec is None:
            return {"ok": False}
        if no_restart:
            rec.max_restarts = 0
        if rec.worker_address:
            try:
                client = self._gcs.daemon_client(rec.node_id)
                if client is not None:
                    await client.call("NodeDaemon", "kill_worker",
                                      worker_address=rec.worker_address,
                                      timeout=5)
            except Exception as e:  # noqa: BLE001
                logger.warning("kill_actor RPC failed: %s", e)
        self._mark_dead(rec, "killed via kill()")
        return {"ok": True}

    def report_actor_failure(self, actor_id: str, reason: str) -> dict:
        """Called by daemons when an actor's worker process exits."""
        rec = self.actors.get(actor_id)
        # RESTARTING means this incarnation's failure was already handled
        # (e.g. node-death path); a second report must not burn another
        # restart or double-enqueue the actor.
        if rec is None or rec.state in (ACTOR_DEAD, ACTOR_RESTARTING):
            return {"ok": False}
        self._handle_failure(rec, reason)
        return {"ok": True}

    # -- internals ------------------------------------------------------
    def _mark_dead(self, rec: ActorRecord, reason: str) -> None:
        self._gcs.event_log.emit(
            "actor", "WARNING",
            f"actor {rec.actor_id[:8]} ({rec.cls_name}) dead: {reason}",
            actor_id=rec.actor_id, reason=reason)
        if rec.detached or (rec.name or "").startswith("serve:"):
            # Journal-worthy deaths only: detached/serve actors are
            # cluster infrastructure (controllers, proxies, prefill
            # workers) — per-job actor churn stays out of the journal.
            self._gcs.flight.record(
                "actor.death",
                f"actor {rec.name or rec.actor_id[:8]} "
                f"({rec.cls_name}) dead: {reason}",
                node_id=rec.node_id or None, severity="WARNING",
                fields={"actor_id": rec.actor_id, "name": rec.name})
        rec.state = ACTOR_DEAD
        rec.death_reason = reason
        rec.worker_address = ""
        if rec.name:
            self.named.pop((rec.namespace, rec.name), None)
        self._publish(rec)

    def _publish(self, rec: ActorRecord) -> None:
        # Every state transition flows through here: one persistence
        # point keeps the durable record in lockstep.
        self._persist(rec)
        self._gcs.pubsub.publish("actor", {
            "actor_id": rec.actor_id, "state": rec.state,
            "worker_address": rec.worker_address,
            "death_reason": rec.death_reason,
        })
        for fut in self._state_waiters.pop(rec.actor_id, ()):
            if not fut.done():
                fut.set_result(None)

    async def wait_actor(self, actor_id: str, known_state: str = "",
                         timeout: float = 2.0) -> Optional[dict]:
        """Long-poll get_actor: return when the actor's state differs
        from `known_state` (immediately if it already does), or after
        `timeout`. Owners resolving a pending actor park HERE instead of
        hammering get_actor on a fixed cadence — at a 1k-actor creation
        storm the 20ms polling loops alone were a double-digit share of
        the control plane's core (ref: the reference's pubsub-driven
        actor state notifications, gcs_actor_manager.h:281)."""
        rec = self.actors.get(actor_id)
        if rec is None or rec.state != known_state:
            return self.get_actor(actor_id=actor_id)
        fut = asyncio.get_running_loop().create_future()
        self._state_waiters.setdefault(actor_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            waiters = self._state_waiters.get(actor_id)
            if waiters is not None:
                try:
                    waiters.remove(fut)
                except ValueError:
                    pass
                if not waiters:
                    self._state_waiters.pop(actor_id, None)
        return self.get_actor(actor_id=actor_id)

    def _handle_failure(self, rec: ActorRecord, reason: str) -> None:
        if rec.state == ACTOR_RESTARTING:
            return  # already queued for rescheduling
        if rec.restarts_used < rec.max_restarts or rec.max_restarts < 0:
            rec.restarts_used += 1
            rec.state = ACTOR_RESTARTING
            rec.worker_address = ""
            self._publish(rec)
            self._pending.put_nowait(rec.actor_id)
            logger.info("actor %s restarting (%d/%s)", rec.actor_id[:8],
                        rec.restarts_used, rec.max_restarts)
            self._gcs.event_log.emit(
                "actor", "WARNING",
                f"actor {rec.actor_id[:8]} restarting "
                f"({rec.restarts_used}/{rec.max_restarts}): {reason}",
                actor_id=rec.actor_id)
            # Serve controller/proxy failover is a cluster transition
            # worth a durable record; plain actor restarts journal only
            # when the actor is detached infrastructure.
            name = rec.name or ""
            if rec.detached or name.startswith("serve:"):
                self._gcs.flight.record(
                    "serve.failover" if name.startswith("serve:")
                    else "actor.failover",
                    f"actor {name or rec.actor_id[:8]} restarting "
                    f"({rec.restarts_used}/{rec.max_restarts}): {reason}",
                    node_id=rec.node_id or None, severity="WARNING",
                    fields={"actor_id": rec.actor_id, "name": rec.name})
        else:
            self._mark_dead(rec, reason)

    def on_node_dead(self, node_id: str) -> None:
        for rec in self.actors.values():
            if rec.node_id == node_id and rec.state in (ACTOR_ALIVE,
                                                        ACTOR_PENDING):
                self._handle_failure(rec, f"node {node_id[:8]} died")

    def on_job_finished(self, job_id: str) -> None:
        for rec in list(self.actors.values()):
            if (not rec.detached and rec.owner_job == job_id
                    and rec.state != ACTOR_DEAD):
                asyncio.ensure_future(self.kill_actor(rec.actor_id))

    async def scheduling_loop(self):
        # Bounded-concurrency scheduling (ref: gcs_actor_scheduler.h —
        # the reference leases workers for many actors in flight at
        # once): a serial loop would cap cluster-wide actor creation at
        # 1/start_actor-latency (~15/s on a small host), no matter how
        # fast the node plane forks. The window is bounded so a burst of
        # creations cannot flood daemons with more concurrent
        # fork+register pipelines than the host can boot at once.
        sem = asyncio.Semaphore(
            max(1, get_config().actor_schedule_concurrency))

        async def requeue(actor_id: str) -> None:
            # Re-queue with a delay (resources may free up) WITHOUT
            # holding a scheduling slot — parked retries must not
            # starve schedulable actors of the window.
            await asyncio.sleep(0.5)
            await self._pending.put(actor_id)

        async def gated(actor_id: str) -> None:
            try:
                rec = self.actors.get(actor_id)
                # Only PENDING/RESTARTING actors may be scheduled; ALIVE
                # means a duplicate queue entry (a second worker would
                # leak), DEAD means the actor was killed while queued.
                if rec is None or rec.state not in (ACTOR_PENDING,
                                                    ACTOR_RESTARTING):
                    return
                try:
                    ok = await self._try_schedule(rec)
                except Exception as e:  # noqa: BLE001
                    logger.exception("actor scheduling error: %s", e)
                    ok = False
                if not ok and rec.state != ACTOR_DEAD:
                    asyncio.ensure_future(requeue(actor_id))
            finally:
                sem.release()

        while True:
            actor_id = await self._pending.get()
            await sem.acquire()
            asyncio.ensure_future(gated(actor_id))

    async def _try_schedule(self, rec: ActorRecord) -> bool:
        view = self._gcs.nodes.view
        node = None
        if rec.placement is not None:
            pg_id, bundle_idx = rec.placement
            node_id = self._gcs.placement_groups.bundle_node(pg_id,
                                                             bundle_idx)
            if node_id is not None:
                node = view.nodes.get(node_id)
        else:
            node = pick_node(view, rec.demand)
        if node is None or not node.alive:
            return False
        client = self._gcs.daemon_client(node.node_id)
        if client is None:
            return False
        try:
            reply = await client.call(
                "NodeDaemon", "start_actor",
                actor_id=rec.actor_id,
                cls_blob_key=rec.cls_blob_key,
                args_blob=rec.args_blob,
                demand=rec.demand,
                runtime_env=rec.runtime_env,
                max_concurrency=rec.max_concurrency,
                concurrency_groups=rec.concurrency_groups,
                placement=rec.placement,
                owner_job=rec.owner_job or "",
                timeout=get_config().actor_creation_timeout_s)
        except Exception as e:  # noqa: BLE001
            logger.warning("start_actor on %s failed: %s", node.node_id[:8],
                           e)
            return False
        if not reply.get("ok"):
            err = reply.get("error", "unknown")
            if reply.get("creation_error"):
                # The user constructor raised — do not retry elsewhere.
                self._mark_dead(rec, f"creation failed: {err}")
                return True
            return False
        if rec.state == ACTOR_DEAD:
            # Killed while the start_actor RPC was in flight: tear down the
            # worker we just started instead of resurrecting the actor.
            try:
                await client.call("NodeDaemon", "kill_worker",
                                  worker_address=reply["worker_address"],
                                  timeout=5)
            except Exception:  # noqa: BLE001
                logger.warning("cleanup kill of %s failed", rec.actor_id[:8])
            return True
        rec.node_id = node.node_id
        rec.worker_address = reply["worker_address"]
        rec.state = ACTOR_ALIVE
        self._publish(rec)
        logger.info("actor %s alive on %s", rec.actor_id[:8],
                    rec.worker_address)
        return True


class ObjectDirectory:
    """Object location registry + distributed free (the centralized stand-in
    for the reference's owner-based directory,
    ref: ownership_based_object_directory.h — centralization trades peak
    scalability for simplicity; the owner remains the refcount authority)."""

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs
        self.locations: Dict[bytes, Set[str]] = defaultdict(set)
        self.sizes: Dict[bytes, int] = {}

    def add_location(self, object_id: bytes, node_id: str,
                     size: int = 0) -> dict:
        self.locations[object_id].add(node_id)
        if size:
            self.sizes[object_id] = size
        return {"ok": True}

    def add_locations(self, entries: List[tuple]) -> dict:
        """Batched registration — one RPC for a burst of task results
        (the hot path batches like the reference's location pubsub)."""
        for object_id, node_id, size in entries:
            self.locations[object_id].add(node_id)
            if size:
                self.sizes[object_id] = size
        return {"ok": True}

    def remove_location(self, object_id: bytes, node_id: str) -> dict:
        self.locations[object_id].discard(node_id)
        return {"ok": True}

    def get_locations(self, object_id: bytes) -> dict:
        nodes = []
        for nid in self.locations.get(object_id, ()):  # only alive nodes
            n = self._gcs.nodes.view.nodes.get(nid)
            if n is not None and n.alive:
                nodes.append({"node_id": nid, "address": n.address,
                              "store_dir": n.store_dir})
        return {"nodes": nodes, "size": self.sizes.get(object_id, 0)}

    async def free_objects(self, object_ids: List[bytes]) -> dict:
        by_node: Dict[str, List[bytes]] = defaultdict(list)
        for oid in object_ids:
            for nid in self.locations.pop(oid, ()):  # consume
                by_node[nid].append(oid)
            self.sizes.pop(oid, None)
        for nid, oids in by_node.items():
            client = self._gcs.daemon_client(nid)
            if client is None:
                continue
            try:
                await client.call("NodeDaemon", "delete_objects",
                                  object_ids=oids, timeout=10)
            except Exception as e:  # noqa: BLE001
                logger.debug("free on %s failed: %s", nid[:8], e)
        return {"ok": True}

    def on_node_dead(self, node_id: str) -> None:
        for oid in list(self.locations):
            self.locations[oid].discard(node_id)


PG_PENDING = "PENDING"
PG_CREATED = "CREATED"
PG_REMOVED = "REMOVED"


@dataclasses.dataclass
class PgRecord:
    pg_id: str
    bundles: List[Dict[str, float]]
    strategy: str
    name: Optional[str] = None
    state: str = PG_PENDING
    # One entry per bundle once any placement happened; None marks a
    # hole (bundle-granular gang repair re-places only the holes while
    # surviving bundles stay reserved on their nodes).
    nodes: List[Optional[str]] = dataclasses.field(default_factory=list)
    owner_job: str = ""
    detached: bool = False
    # Soft per-bundle node-label preferences (ICI-topology ordering
    # hint from tpu_slice_placement_group).
    bundle_labels: Optional[List[Optional[Dict[str, str]]]] = None


class PlacementGroupManager:
    """Gang resource reservation (ref: gcs_placement_group_manager.h:230,
    gcs_placement_group_scheduler.h:274 — prepare/commit two-phase). On TPU
    the flagship use is slice-atomic gangs: one bundle per host of a slice,
    STRICT_PACK within an ICI domain."""

    def __init__(self, gcs: "GcsServer", store=None):
        from ray_tpu.core.distributed.gcs_storage import NullStore

        self._gcs = gcs
        self._store = store or NullStore()
        self.groups: Dict[str, PgRecord] = {}
        self._pending: asyncio.Queue = asyncio.Queue()
        # Long-poll wait_pg futures, woken on any state transition.
        self._state_waiters: Dict[str, List[asyncio.Future]] = {}
        for rec_dict in self._store.all("pg").values():
            rec = PgRecord(**rec_dict)
            self.groups[rec.pg_id] = rec

    def requeue_loaded(self) -> None:
        for rec in self.groups.values():
            if rec.state == PG_PENDING:
                self._pending.put_nowait(rec.pg_id)
        created = [r.pg_id for r in self.groups.values()
                   if r.state == PG_CREATED]
        if created:
            asyncio.ensure_future(self._validate_loaded(created))

    async def _validate_loaded(self, pg_ids: List[str]) -> None:
        """A loaded CREATED gang whose host died during the GCS outage
        must re-form: the node never re-registers, so on_node_dead would
        never fire for it (the PG analogue of actor revalidation)."""
        await asyncio.sleep(get_config().health_check_period_ms / 1000
                            * get_config().health_check_failure_threshold)
        view = self._gcs.nodes.view
        for pg_id in pg_ids:
            rec = self.groups.get(pg_id)
            if rec is None or rec.state != PG_CREATED:
                continue
            missing = [nid for nid in rec.nodes
                       if nid is not None
                       and (nid not in view.nodes
                            or not view.nodes[nid].alive)]
            if missing:
                logger.warning(
                    "pg %s lost node(s) %s during GCS outage; "
                    "re-reserving the lost bundles", pg_id[:8],
                    [m[:8] for m in missing])
                rec.nodes = [None if nid in missing else nid
                             for nid in rec.nodes]
                rec.state = PG_PENDING
                self._persist(rec)
                self._wake_waiters(pg_id)
                self._pending.put_nowait(pg_id)

    def _persist(self, rec: PgRecord) -> None:
        self._store.put("pg", rec.pg_id, dataclasses.asdict(rec))

    def _wake_waiters(self, pg_id: str) -> None:
        for fut in self._state_waiters.pop(pg_id, ()):
            if not fut.done():
                fut.set_result(None)

    async def create_pg(self, pg_id: str, bundles: List[Dict[str, float]],
                        strategy: str, name: Optional[str] = None,
                        owner_job: str = "", detached: bool = False,
                        bundle_labels: Optional[List[Optional[Dict[
                            str, str]]]] = None) -> dict:
        rec = PgRecord(pg_id=pg_id, bundles=bundles, strategy=strategy,
                       name=name, owner_job=owner_job, detached=detached,
                       bundle_labels=bundle_labels)
        self.groups[pg_id] = rec
        self._persist(rec)
        await self._pending.put(pg_id)
        return {"pg_id": pg_id}

    def get_pg(self, pg_id: str) -> Optional[dict]:
        rec = self.groups.get(pg_id)
        if rec is None:
            return None
        return {"pg_id": rec.pg_id, "state": rec.state, "nodes": rec.nodes,
                "bundles": rec.bundles, "strategy": rec.strategy,
                "placed": sum(1 for n in rec.nodes if n is not None),
                "bundle_count": len(rec.bundles)}

    async def wait_pg(self, pg_id: str, known_state: str = "",
                      park_s: float = 2.0) -> Optional[dict]:
        """Long-poll get_pg (same pattern as ActorManager.wait_actor):
        return when the gang's state differs from `known_state`
        (immediately if it already does), or after `timeout`. Drivers
        blocking in PlacementGroup.ready() park here instead of
        polling get_pg on a 50ms cadence."""
        rec = self.groups.get(pg_id)
        if rec is None or rec.state != known_state:
            return self.get_pg(pg_id)
        fut = asyncio.get_running_loop().create_future()
        self._state_waiters.setdefault(pg_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, park_s)
        except asyncio.TimeoutError:
            waiters = self._state_waiters.get(pg_id)
            if waiters is not None:
                try:
                    waiters.remove(fut)
                except ValueError:
                    pass
                if not waiters:
                    self._state_waiters.pop(pg_id, None)
        return self.get_pg(pg_id)

    def list_pgs(self) -> List[dict]:
        return [self.get_pg(pid) for pid in self.groups]

    def bundle_node(self, pg_id: str, bundle_idx: int) -> Optional[str]:
        rec = self.groups.get(pg_id)
        if rec is None or rec.state != PG_CREATED:
            return None
        if bundle_idx < 0:
            return rec.nodes[0] if rec.nodes else None
        if bundle_idx >= len(rec.nodes):
            return None
        return rec.nodes[bundle_idx]

    async def remove_pg(self, pg_id: str) -> dict:
        rec = self.groups.get(pg_id)
        if rec is None or rec.state == PG_REMOVED:
            return {"ok": False}
        for idx, nid in enumerate(rec.nodes):
            if nid is None:
                continue
            client = self._gcs.daemon_client(nid)
            if client is None:
                continue
            try:
                await client.call("NodeDaemon", "return_pg_bundle",
                                  pg_id=pg_id, bundle_idx=idx, timeout=10)
            except Exception:  # noqa: BLE001
                pass
        rec.state = PG_REMOVED
        rec.nodes = []
        self._persist(rec)
        self._wake_waiters(pg_id)
        return {"ok": True}

    def on_node_dead(self, node_id: str) -> None:
        for rec in self.groups.values():
            if rec.state == PG_REMOVED or node_id not in rec.nodes:
                continue
            # Bundle-granular recovery: only the dead node's bundles
            # become holes; surviving bundles stay reserved on their
            # nodes while the scheduler re-places the holes (the
            # elastic supervisor meanwhile keeps ranks on the
            # survivors warm for the gang restart).
            rec.nodes = [None if nid == node_id else nid
                         for nid in rec.nodes]
            was_created = rec.state == PG_CREATED
            rec.state = PG_PENDING
            self._persist(rec)
            self._gcs.event_log.emit(
                "placement_group", "WARNING",
                f"pg {rec.pg_id[:8]} gang lost node "
                f"{node_id[:8]}; re-reserving "
                f"{sum(1 for n in rec.nodes if n is None)} bundle(s)",
                pg_id=rec.pg_id)
            self._gcs.flight.record(
                "pg.repair",
                f"pg {rec.pg_id[:8]} gang lost node {node_id[:8]}; "
                f"re-reserving "
                f"{sum(1 for n in rec.nodes if n is None)} bundle(s)",
                node_id=node_id, severity="WARNING",
                fields={"pg_id": rec.pg_id})
            if was_created:
                self._wake_waiters(rec.pg_id)
            self._pending.put_nowait(rec.pg_id)

    def on_job_finished(self, job_id: str) -> None:
        for rec in list(self.groups.values()):
            if (not rec.detached and rec.owner_job == job_id
                    and rec.state != PG_REMOVED):
                asyncio.ensure_future(self.remove_pg(rec.pg_id))

    async def scheduling_loop(self):
        while True:
            pg_id = await self._pending.get()
            rec = self.groups.get(pg_id)
            if rec is None or rec.state != PG_PENDING:
                continue
            ok = await self._try_reserve(rec)
            if not ok and rec.state == PG_PENDING:
                async def requeue(pid=pg_id):
                    await asyncio.sleep(0.5)
                    await self._pending.put(pid)

                asyncio.ensure_future(requeue())

    async def _return_bundles(self, pg_id: str,
                              reserved: List[Tuple[str, int]]) -> None:
        for rnid, ridx in reserved:
            rclient = self._gcs.daemon_client(rnid)
            if rclient is not None:
                try:
                    await rclient.call("NodeDaemon", "return_pg_bundle",
                                       pg_id=pg_id, bundle_idx=ridx,
                                       timeout=10)
                except Exception:  # noqa: BLE001
                    pass

    async def _call_bundle(self, nid: str, method: str, **kwargs) -> bool:
        client = self._gcs.daemon_client(nid)
        if client is None:
            return False
        try:
            reply = await client.call("NodeDaemon", method,
                                      timeout=10, **kwargs)
            return bool(reply.get("ok", False))
        except Exception:  # noqa: BLE001
            return False

    async def _try_reserve(self, rec: PgRecord) -> bool:
        """Two-phase atomic gang reserve (ref:
        gcs_placement_group_scheduler.h:274 PREPARE then COMMIT).

        All missing bundles are PREPAREd concurrently; any failure rolls
        back every bundle prepared this round, so a half-placed gang
        never leaks (daemon-side prepare TTLs backstop a GCS crash
        between phases). Only after every prepare lands does COMMIT make
        the bundles usable — and trigger the per-bundle worker prewarm.
        Bundles already placed from a previous round (`rec.nodes`
        non-None entries — gang repair after a node death) are kept, not
        re-reserved."""
        nodes_snapshot = list(rec.nodes)
        preplaced: List[Optional[str]] = (
            list(rec.nodes) if len(rec.nodes) == len(rec.bundles)
            else [None] * len(rec.bundles))
        placement = place_bundles(self._gcs.nodes.view, rec.bundles,
                                  rec.strategy, preplaced=preplaced,
                                  bundle_labels=rec.bundle_labels)
        if placement is None:
            return False
        new_idxs = [i for i, pre in enumerate(preplaced) if pre is None]
        if new_idxs:
            prepared = await asyncio.gather(*[
                self._call_bundle(placement[i], "reserve_pg_bundle",
                                  pg_id=rec.pg_id, bundle_idx=i,
                                  resources=rec.bundles[i])
                for i in new_idxs])
            this_round = [(placement[i], i)
                          for i, ok in zip(new_idxs, prepared) if ok]
            if not all(prepared):
                await self._return_bundles(rec.pg_id, this_round)
                return False
            committed = await asyncio.gather(*[
                self._call_bundle(placement[i], "commit_pg_bundle",
                                  pg_id=rec.pg_id, bundle_idx=i)
                for i in new_idxs])
            if not all(committed):
                # A daemon died (or expired the prepare) between the
                # phases: the gang is not whole — release this round
                # and retry from the survivors.
                await self._return_bundles(rec.pg_id, this_round)
                return False
        if rec.state == PG_REMOVED:
            # remove_pg ran while we were reserving: it returned the
            # bundles it knew of (rec.nodes at the time), not this
            # round's — release those here.
            await self._return_bundles(
                rec.pg_id, [(placement[i], i) for i in new_idxs])
            return True
        if list(rec.nodes) != nodes_snapshot:
            # on_node_dead punched holes mid-reserve: committing
            # `placement` would resurrect a dead node's bundle. Release
            # this round and re-place against the updated holes.
            await self._return_bundles(
                rec.pg_id, [(placement[i], i) for i in new_idxs])
            return False
        rec.nodes = placement
        rec.state = PG_CREATED
        self._persist(rec)
        self._gcs.event_log.emit(
            "placement_group", "INFO",
            f"pg {rec.pg_id[:8]} gang committed "
            f"({len(new_idxs)}/{len(rec.bundles)} bundles new)",
            pg_id=rec.pg_id)
        if new_idxs:
            self._gcs.flight.record(
                "pg.commit",
                f"pg {rec.pg_id[:8]} gang committed "
                f"({len(new_idxs)}/{len(rec.bundles)} bundles new)",
                fields={"pg_id": rec.pg_id, "nodes": list(placement)})
        self._gcs.pubsub.publish("pg", {"pg_id": rec.pg_id,
                                        "state": PG_CREATED,
                                        "nodes": placement})
        self._wake_waiters(rec.pg_id)
        return True


class JobManager:
    """Driver/job registry (ref: gcs_job_manager.h)."""

    def __init__(self, gcs: "GcsServer", store=None):
        from ray_tpu.core.distributed.gcs_storage import NullStore

        self._gcs = gcs
        self._store = store or NullStore()
        self.jobs: Dict[str, dict] = dict(self._store.all("job"))

    def register_job(self, job_id: str, driver_address: str,
                     metadata: Optional[dict] = None) -> dict:
        self.jobs[job_id] = {
            "job_id": job_id, "driver_address": driver_address,
            "start_time": time.time(), "finished": False,
            "metadata": metadata or {},
        }
        self._store.put("job", job_id, self.jobs[job_id])
        return {"ok": True}

    def finish_job(self, job_id: str) -> dict:
        job = self.jobs.get(job_id)
        if job is not None:
            job["finished"] = True
            job["end_time"] = time.time()
            self._store.put("job", job_id, job)
        self._gcs.actors.on_job_finished(job_id)
        self._gcs.placement_groups.on_job_finished(job_id)
        self._gcs.task_events.on_job_finished(job_id)
        return {"ok": True}

    def list_jobs(self) -> List[dict]:
        return list(self.jobs.values())


class EventLog:
    """Structured cluster event log (ref: src/ray/util/event.h RAY_EVENT
    macros + the dashboard event module): node/actor/PG lifecycle events
    with severity, queryable via `ray-tpu list events` and the dashboard.
    """

    # Decision sources whose events arrive over RPC (the elastic
    # supervisor's resize decisions, autoscaler verdicts) and must also
    # land in the durable flight recorder — their direct emitters live
    # outside the GCS process, so the mirror is the one hook point.
    MIRRORED_SOURCES = ("elastic", "autoscaler")

    def __init__(self, max_events: int = 20000):
        self.events: deque = deque(maxlen=max_events)
        self.flight: Optional["FlightRecorder"] = None  # set by GcsServer

    def emit(self, source: str, severity: str, message: str,
             **fields) -> dict:
        self.events.append({
            "ts": time.time(), "source": source,
            "severity": severity, "message": message, **fields,
        })
        if self.flight is not None and source in self.MIRRORED_SOURCES:
            self.flight.record(source, message, severity=severity,
                               fields=fields or None)
        return {"ok": True}

    def add_event(self, source: str, severity: str, message: str,
                  fields: Optional[dict] = None) -> dict:
        # Reserved keys would collide with emit()'s own parameters (a
        # caller 'message'/'ts' must not TypeError or clobber the
        # timestamp); namespace them.
        clean = {(f"field_{k}" if k in ("source", "severity", "message",
                                        "ts", "self") else k): v
                 for k, v in (fields or {}).items()}
        return self.emit(source, severity, message, **clean)

    def list_events(self, source: Optional[str] = None,
                    severity: Optional[str] = None,
                    limit: int = 1000) -> List[dict]:
        out = []
        for e in reversed(self.events):
            if source is not None and e["source"] != source:
                continue
            if severity is not None and e["severity"] != severity:
                continue
            out.append(e)
            if len(out) >= limit:
                break
        return out


class FlightRecorder:
    """Cluster flight recorder: a bounded, PersistentStore-durable
    journal of state transitions that previously vanished into logs —
    node join/death/re-registration, controller/proxy failover, drain +
    KV migration, autoscale and elastic resize decisions, PG repair.
    Queryable by time/kind/node via `state.cluster_events()` /
    `ray-tpu events`, and it survives a GCS restart: entries are
    persisted to the same store that backs KV/actors/PGs, so the
    post-recovery journal still explains how the cluster got here.

    The on-loop cost of ``record()`` is a deque append plus an executor
    handoff; the fsyncing store write always runs OFF the GCS loop
    (pinned by the lint suite's `no-blocking-in-loop` journal registry).
    """

    _RESERVED = ("seq", "ts", "kind", "severity", "message", "node_id",
                 "self")

    def __init__(self, gcs: "GcsServer", store=None):
        from ray_tpu.core.distributed.gcs_storage import NullStore

        cfg = get_config()
        self._gcs = gcs
        self._store = store or NullStore()
        self._enabled = cfg.gcs_flight_recorder_enabled
        self._max = max(16, cfg.gcs_flight_max_events)
        self.events: deque = deque()
        self._seq = 0
        # Boot-load the journal the last GCS incarnation left behind
        # (constructor runs before the server accepts RPCs, so blocking
        # store reads are fine here — same as the KV table load).
        for seq, entry in sorted(self._store.all("flight").items()):
            self.events.append(entry)
            try:
                self._seq = max(self._seq, int(seq))
            except (TypeError, ValueError):
                pass
        while len(self.events) > self._max:
            evicted = self.events.popleft()
            self._store.delete("flight", evicted.get("seq"))

    def record(self, kind: str, message: str,
               node_id: Optional[str] = None, severity: str = "INFO",
               fields: Optional[dict] = None) -> dict:
        """Journal one state transition (also the RPC entry point, so
        out-of-process components can journal through the GCS)."""
        if not self._enabled:
            return {"ok": False, "disabled": True}
        clean = {(f"field_{k}" if k in self._RESERVED else k): v
                 for k, v in (fields or {}).items()}
        self._seq += 1
        entry = {"seq": self._seq, "ts": time.time(), "kind": kind,
                 "severity": severity, "message": message,
                 "node_id": node_id, **clean}
        self.events.append(entry)
        evict = self.events.popleft() if len(self.events) > self._max \
            else None
        self._schedule_persist(entry, evict)
        return {"ok": True, "seq": self._seq}

    def _schedule_persist(self, entry: dict, evict: Optional[dict]
                          ) -> None:
        # The store write fsyncs under a lock — never on the GCS loop.
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            # No running loop (unit tests, boot): synchronous is safe.
            self._persist(entry, evict)
            return
        loop.run_in_executor(None, self._persist, entry, evict)

    def _persist(self, entry: dict, evict: Optional[dict]) -> None:
        try:
            self._store.put("flight", entry["seq"], entry)
            if evict is not None:
                self._store.delete("flight", evict.get("seq"))
        except Exception:  # noqa: BLE001 — journal is best-effort
            pass

    def list_events(self, kind: Optional[str] = None,
                    node_id: Optional[str] = None,
                    since: Optional[float] = None,
                    until: Optional[float] = None,
                    limit: int = 200) -> List[dict]:
        """Newest-first scan with time/kind/node filters; the result is
        returned oldest-first (a readable timeline)."""
        out: List[dict] = []
        for e in reversed(self.events):
            if since is not None and e["ts"] < since:
                break  # deque is time-ordered; the rest is older still
            if until is not None and e["ts"] > until:
                continue
            if kind is not None and not e["kind"].startswith(kind):
                continue
            if node_id is not None and e.get("node_id") != node_id:
                continue
            out.append(e)
            if len(out) >= limit:
                break
        out.reverse()
        return out

    def kinds(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for e in self.events:
            counts[e["kind"]] = counts.get(e["kind"], 0) + 1
        return counts

    def stats(self) -> dict:
        return {"enabled": self._enabled, "events": len(self.events),
                "seq": self._seq, "max_events": self._max,
                "kinds": self.kinds(),
                "durable": type(self._store).__name__ != "NullStore"}


def _arg_digest(value: Any) -> str:
    """Compact, bounded description of one handler argument for the
    slow-handler audit — sizes for payloads, truncated reprs for the
    rest (never the full value: a 10 MB blob must not become a 10 MB
    log line)."""
    if isinstance(value, (bytes, bytearray, memoryview)):
        return f"bytes[{len(value)}]"
    if isinstance(value, (list, tuple, set)):
        return f"{type(value).__name__}[{len(value)}]"
    if isinstance(value, dict):
        return f"dict[{len(value)}]"
    text = repr(value)
    return text if len(text) <= 48 else text[:45] + "..."


class GcsLoadAttribution:
    """GCS load attribution (tentpole of the measure-then-shard arc):
    every handled RPC is accounted per (service, caller component) —
    requests, bytes in, handler wall time — via the RpcServer
    attribution sink, with caller identity riding the reserved
    `_caller` kwarg rpc.py injects client-side. `shares()` turns the
    raw accumulators into the per-service x per-component load shares
    `ray-tpu gcs top` renders and the sharding PR will cite.

    Also the slow-handler audit: any handler exceeding
    RAY_TPU_GCS_SLOW_HANDLER_MS is logged with method + caller + a
    bounded args digest (built lazily — fast handlers never pay for
    repr) and kept in a small recent-ring for `ray-tpu doctor`."""

    SLOW_KEEP = 32

    def __init__(self, gcs: "GcsServer"):
        cfg = get_config()
        self._gcs = gcs
        self._t0 = time.time()
        # (service, component) -> [requests, bytes, handler_seconds]
        self._by: Dict[Tuple[str, str], List[float]] = {}
        self._slow_budget_s = max(0.0, cfg.gcs_slow_handler_ms) / 1000.0
        self.slow_total = 0
        self._slow_recent: deque = deque(maxlen=self.SLOW_KEEP)

    def sink(self, attr: tuple, seconds: float, kwargs: dict,
             stream: bool = False) -> None:
        """Installed as RpcServer.attribution_sink — one dict upsert
        per handled RPC is the entire on-loop cost of attribution.
        A stream's `seconds` is its open lifetime (await-time, not
        loop occupancy): count the request and bytes, skip the time
        accumulators and the slow-handler audit."""
        service, method, caller, nbytes = attr
        component = caller[1] if caller else "unknown"
        slot = self._by.get((service, component))
        if slot is None:
            slot = self._by[(service, component)] = [0, 0, 0.0]
        slot[0] += 1
        slot[1] += nbytes
        if stream:
            return
        slot[2] += seconds
        if self._slow_budget_s and seconds >= self._slow_budget_s:
            self._record_slow(service, method, caller, seconds, kwargs)

    def _record_slow(self, service: str, method: str,
                     caller: Optional[tuple], seconds: float,
                     kwargs: dict) -> None:
        digest = ", ".join(f"{k}={_arg_digest(v)}"
                           for k, v in list(kwargs.items())[:8])
        who = f"{caller[1]}@{caller[0][:8]}" if caller else "unknown"
        entry = {"ts": time.time(), "service": service, "method": method,
                 "caller": list(caller) if caller else None,
                 "wall_ms": round(seconds * 1000.0, 3), "args": digest}
        self.slow_total += 1
        self._slow_recent.append(entry)
        logger.warning(
            "slow GCS handler %s.%s: %.1fms (budget %.0fms) caller=%s "
            "args=[%s]", service, method, seconds * 1000.0,
            self._slow_budget_s * 1000.0, who, digest)
        self._gcs.event_log.emit(
            "gcs", "WARNING",
            f"slow handler {service}.{method}: "
            f"{seconds * 1000.0:.1f}ms (caller {who})",
            service=service, method=method, wall_ms=entry["wall_ms"])

    def shares(self) -> dict:
        """Per-service x per-component request/bytes/handler-time load
        shares since GCS boot, plus the per-component handler-time
        rollup the doctor's top finding quotes."""
        total_req, total_bytes, total_s = 0, 0, 0.0
        for reqs, nbytes, secs in self._by.values():
            total_req += reqs
            total_bytes += nbytes
            total_s += secs
        rows = []
        for (service, component), (reqs, nbytes, secs) in sorted(
                self._by.items(), key=lambda kv: -kv[1][2]):
            rows.append({
                "service": service, "component": component,
                "requests": reqs, "bytes": nbytes,
                "handler_s": round(secs, 6),
                "requests_share": round(reqs / total_req, 4)
                if total_req else 0.0,
                "bytes_share": round(nbytes / total_bytes, 4)
                if total_bytes else 0.0,
                "handler_share": round(secs / total_s, 4)
                if total_s else 0.0,
            })
        by_comp: Dict[str, float] = {}
        for (_service, component), (_r, _b, secs) in self._by.items():
            by_comp[component] = by_comp.get(component, 0.0) + secs
        comp_shares = {c: (round(s / total_s, 4) if total_s else 0.0)
                       for c, s in sorted(by_comp.items(),
                                          key=lambda kv: -kv[1])}
        return {
            "window_s": round(time.time() - self._t0, 1),
            "total": {"requests": total_req, "bytes": total_bytes,
                      "handler_s": round(total_s, 6)},
            "rows": rows,
            "component_handler_share": comp_shares,
            "slow_handlers": {
                "total": self.slow_total,
                "budget_ms": round(self._slow_budget_s * 1000.0, 1),
                "recent": list(self._slow_recent),
            },
        }


class MetricsFederation:
    """Cluster-wide metrics view (the analogue of Prometheus federation
    over the reference's per-node metrics agents): nodes piggyback
    registry snapshots on their syncer pushes; this manager merges them
    — each sample gaining a `node` label — into one exposition served
    over RPC (`Metrics.federated_text`) and, with
    RAY_TPU_METRICS_GCS_EXPORT_PORT set, over HTTP on the GCS."""

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs
        # node_id -> {"ts": wall time received, "dump": registry_dump()}
        self._node_dumps: Dict[str, dict] = {}

    def ingest(self, node_id: str, dump: List[dict]) -> None:
        self._node_dumps[node_id] = {"ts": time.time(), "dump": dump}

    def on_node_dead(self, node_id: str) -> None:
        self._node_dumps.pop(node_id, None)

    def federated_text(self) -> str:
        from ray_tpu.util.metrics import merge_dumps, registry_dump

        dumps = {nid[:12]: rec["dump"]
                 for nid, rec in self._node_dumps.items()}
        # The GCS's own process metrics (RPC handler histograms,
        # event-loop lag/backlog, KV + flight-journal sizes) ride the
        # same exposition, labelled with the GCS's node identity so
        # multi-cluster scrapes stay distinguishable.
        dumps[f"gcs:{self._gcs.node_id[:12]}"] = registry_dump()
        return merge_dumps(dumps)

    def stats(self) -> dict:
        now = time.time()
        return {
            "nodes_reporting": len(self._node_dumps),
            "staleness_s": {nid[:12]: round(now - rec["ts"], 3)
                            for nid, rec in self._node_dumps.items()},
        }

    def cluster_summary(self) -> dict:
        """One-RPC observability rollup for `ray-tpu status` /
        state.cluster_status callers: federation freshness, task-event
        completeness accounting, and the watchdog's live hung-task
        list."""
        return {
            "metrics": self.stats(),
            "task_events": self._gcs.task_events.stats(),
            "hung_tasks": self._gcs.task_events.hung_tasks(),
            "serve": self._gcs.serve_gauges.summary(),
            "train": self._gcs.train_runs.summary(),
            "gcs": self.gcs_load(),
        }

    def gcs_load(self) -> dict:
        """Control-plane self-observability blob: attribution shares,
        the event-loop audit, and flight-journal stats — served both
        standalone (`ray-tpu gcs top`) and inside cluster_summary."""
        return {
            "node_id": self._gcs.node_id,
            "load": self._gcs.attribution.shares(),
            "loop": dict(self._gcs.loop_audit),
            "flight": self._gcs.flight.stats(),
        }

    # -- doctor ---------------------------------------------------------
    #
    # Heuristic thresholds (share of GCS handler time worth flagging,
    # loop lag, recent-death window) — tuned to flag real saturation
    # without firing on an idle two-node cluster.
    DOCTOR_SHARE_WARN = 0.35
    DOCTOR_MIN_HANDLER_S = 0.05
    DOCTOR_LAG_WARN_S = 0.25
    DOCTOR_DEATH_WINDOW_S = 600.0
    # Train-plane findings: share of attributed step time spent waiting
    # on input before a run is called input-bound, the p99/p50 step-time
    # ratio that flags a straggler rank, the goodput floor under restart
    # churn, and how recently a run must have reported to be examined.
    DOCTOR_TRAIN_INPUT_SHARE = 0.25
    DOCTOR_TRAIN_SKEW = 1.5
    DOCTOR_TRAIN_GOODPUT = 0.5
    DOCTOR_TRAIN_WINDOW_S = 600.0
    DOCTOR_TRAIN_MIN_ATTRIBUTED_S = 0.5

    _SHARE_HINTS = {
        "serve-gauges": "raise RAY_TPU_SERVE_METRICS_PUSH_S",
        "syncer": "raise RAY_TPU_METRICS_SYNC_INTERVAL_MS",
        "task-events": "raise RAY_TPU_TASK_EVENTS_FLUSH_MS or lower "
                       "RAY_TPU_TASK_EVENTS_MAX_BUFFER",
        "scheduler": "check heartbeat cadence / lease churn "
                     "(RAY_TPU_HEALTH_CHECK_PERIOD_MS)",
        "client": "batch driver-side GCS reads",
    }

    def doctor(self) -> dict:
        """One fused health report: federated metrics freshness, hung
        tasks, task-event drop/eviction counters, GCS load shares, the
        event-loop audit, and recent flight-recorder entries — ranked
        findings, highest score first, each with an actionable hint."""
        gcs = self._gcs
        now = time.time()
        findings: List[dict] = []

        def add(kind: str, severity: str, score: float, message: str,
                hint: str, **extra) -> None:
            findings.append({"kind": kind, "severity": severity,
                             "score": round(score, 1),
                             "message": message, "hint": hint, **extra})

        load = gcs.attribution.shares()
        total_s = load["total"]["handler_s"]
        for comp, share in load["component_handler_share"].items():
            if (comp != "unknown" and share >= self.DOCTOR_SHARE_WARN
                    and total_s >= self.DOCTOR_MIN_HANDLER_S):
                add("gcs-load", "warning", 40 + share * 55,
                    f"component '{comp}' is {share:.0%} of GCS handler "
                    f"time ({total_s:.2f}s total)",
                    self._SHARE_HINTS.get(
                        comp, "profile this component's GCS call sites"),
                    component=comp, share=share)
        slow = load["slow_handlers"]
        if slow["total"]:
            worst = max(slow["recent"], key=lambda e: e["wall_ms"],
                        default=None)
            add("gcs-slow-handler", "warning",
                45 + min(20.0, slow["total"]),
                f"{slow['total']} GCS handler(s) exceeded the "
                f"{slow['budget_ms']:.0f}ms budget"
                + (f" (worst: {worst['service']}.{worst['method']} "
                   f"{worst['wall_ms']:.0f}ms)" if worst else ""),
                "inspect the slow-handler log lines; raise "
                "RAY_TPU_GCS_SLOW_HANDLER_MS only if expected",
                recent=slow["recent"][-3:])
        lag = gcs.loop_audit.get("lag_max_s", 0.0)
        if lag >= self.DOCTOR_LAG_WARN_S:
            add("gcs-loop-lag",
                "critical" if lag >= 4 * self.DOCTOR_LAG_WARN_S
                else "warning", 60 + min(30.0, lag * 10),
                f"GCS event loop lagged up to {lag * 1000:.0f}ms",
                "a handler or import is blocking the loop; check the "
                "slow-handler audit and gcs-load shares", lag_s=lag)
        hung = gcs.task_events.hung_tasks(limit=10)
        if hung:
            oldest = min(h.get("hung_ts") or now for h in hung)
            add("hung-tasks", "critical", 85 + min(10.0, len(hung)),
                f"{len(hung)} task(s) flagged hung "
                f"(oldest {now - oldest:.0f}s ago)",
                "`ray-tpu stack <node>` for live tracebacks; see "
                "attached auto-captured dumps", tasks=hung[:5])
        te = gcs.task_events.stats()
        dropped = (te.get("worker_dropped_status", 0)
                   + te.get("worker_dropped_profile", 0))
        evicted = te.get("evicted", 0)
        if dropped or evicted:
            add("task-event-loss", "info",
                20 + min(20.0, (dropped + evicted) / 1000),
                f"task-event telemetry is incomplete: {dropped} dropped "
                f"worker-side, {evicted} evicted by the GCS cap",
                "raise RAY_TPU_TASK_EVENTS_MAX_BUFFER / "
                "RAY_TPU_TASK_EVENTS_MAX_PER_JOB if completeness "
                "matters", dropped=dropped, evicted=evicted)
        deaths = [e for e in gcs.flight.list_events(kind="node.death",
                                                    limit=50)
                  if now - e["ts"] <= self.DOCTOR_DEATH_WINDOW_S]
        if deaths:
            add("node-churn", "warning", 70 + min(15.0, len(deaths) * 3),
                f"{len(deaths)} node death(s) in the last "
                f"{self.DOCTOR_DEATH_WINDOW_S / 60:.0f}min "
                f"(latest: {deaths[-1]['message']})",
                "`ray-tpu events --kind node` for the timeline; check "
                "host health / preemptions",
                nodes=[e.get("node_id") for e in deaths[-5:]])
        cfg = get_config()
        stale_after = max(3 * cfg.metrics_sync_interval_ms / 1000.0, 10.0)
        stale = {nid: s for nid, s in self.stats()["staleness_s"].items()
                 if s > stale_after}
        if stale:
            add("stale-metrics", "warning", 55 + min(15.0, len(stale) * 3),
                f"{len(stale)} node(s) have not shipped metrics for "
                f">{stale_after:.0f}s: {sorted(stale)[:5]}",
                "their syncer pushes are stalling — check daemon health",
                nodes=stale)
        for run, s in gcs.train_runs.summary()["runs"].items():
            if s["last_seen_age_s"] > self.DOCTOR_TRAIN_WINDOW_S:
                continue
            attributed = sum(v for k, v in s["attributed_s"].items()
                             if k != "step_s")
            split, skew = s["split"], s["skew"]
            if (split and attributed >= self.DOCTOR_TRAIN_MIN_ATTRIBUTED_S
                    and split["data_wait"] >= self.DOCTOR_TRAIN_INPUT_SHARE):
                add("train-input-bound", "warning",
                    50 + split["data_wait"] * 40,
                    f"train run '{run}' is input-bound: "
                    f"{split['data_wait']:.0%} of step time waiting on "
                    f"the input pipeline",
                    "raise the ingest prefetch depth "
                    "(RAY_TPU_DATA_STREAM_PREFETCH_DEPTH) or dataset "
                    "read parallelism; `ray-tpu train trace` shows the "
                    "per-step data_wait slices", run=run,
                    data_wait_share=split["data_wait"])
            stale = skew.get("stale_ranks")
            # Straggler verdicts only make sense while the run is live:
            # a finished run's ranks all go quiet, which is not a
            # straggler — the other findings describe cumulative facts
            # and stay useful for the whole recency window.
            if s["active"] and (stale or skew.get("ratio", 0.0)
                                >= self.DOCTOR_TRAIN_SKEW):
                blame = skew.get("blame_rank")
                why = (f"rank(s) {stale} stopped reporting "
                       f"(SIGSTOP/livelock?)" if stale else
                       f"p99/p50 step time = {skew['ratio']:.2f}")
                add("train-straggler",
                    "critical" if stale else "warning",
                    65 + (20 if stale else min(15.0, skew.get("ratio", 0))),
                    f"train run '{run}' has a persistent straggler: "
                    f"rank {blame} ({why})",
                    "`ray-tpu stack <node>` the blamed rank's host; a "
                    "stopped rank is replaced by the elastic supervisor "
                    "once RAY_TPU_HANG_THRESHOLD_S expires",
                    run=run, blame_rank=blame, skew=skew)
            if (s["restarts"] >= 1 and s["goodput"] is not None
                    and s["goodput"] < self.DOCTOR_TRAIN_GOODPUT):
                add("train-churn-goodput", "warning",
                    55 + min(25.0, s["restarts"] * 5),
                    f"train run '{run}' goodput is {s['goodput']:.0%} "
                    f"after {s['restarts']} restart(s) "
                    f"({s['lost_restart_s']:.0f}s lost to restart gaps)",
                    "check `ray-tpu list events --source elastic` for "
                    "the causes; longer-lived checkpoints shrink the "
                    "replay, RAY_TPU_ELASTIC_BACKOFF_* shrinks the gap",
                    run=run, goodput=s["goodput"],
                    restarts=s["restarts"])
        findings.sort(key=lambda f: -f["score"])
        return {"ts": now, "healthy": not findings,
                "findings": findings,
                "checks": ["gcs-load", "gcs-slow-handler", "gcs-loop-lag",
                           "hung-tasks", "task-event-loss", "node-churn",
                           "stale-metrics", "train-input-bound",
                           "train-straggler", "train-churn-goodput"]}


class ServeGauges:
    """Cluster-merged serve replica gauges (the autoscaling read side of
    the syncer plane): replicas push gauges to their node daemon, the
    daemon's `serve` state key rides its syncer delta here, and the
    serve controller reads ONE merged per-app view per reconcile tick —
    no per-decision replica polling."""

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs

    def merged(self) -> Dict[str, dict]:
        """Fold every alive node's per-app aggregate into a cluster-wide
        per-app aggregate (sums of replicas / queue_depth / active;
        occupancy stays a sum too — the controller divides by replicas
        for a mean)."""
        out: Dict[str, Dict[str, Any]] = {}
        for n in self._gcs.nodes.view.alive_nodes():
            for app, agg in (getattr(n, "serve", None) or {}).items():
                dst = out.setdefault(app, {})
                for name, val in agg.items():
                    # Per-replica disagg state (role + prefix digests)
                    # is a union across nodes, not a sum.
                    if name == "_replicas" and isinstance(val, dict):
                        dst.setdefault("_replicas", {}).update(val)
                        continue
                    try:
                        dst[name] = round(dst.get(name, 0.0) + float(val),
                                          3)
                    except (TypeError, ValueError):
                        continue
        return out

    def summary(self) -> dict:
        """`ray-tpu serve status` / cluster_status()["observability"]
        ["serve"] payload: the merged autoscaling gauges plus a
        latency/counter rollup mined from the federated serve metrics —
        per-app TTFT/ITL means, per-phase means (queue_wait / prefill /
        decode_step / stream_transport), and the serve counter totals
        (tokens, requests by status, KV events, sheds, resumes)."""
        lat: Dict[str, dict] = {}
        counters: Dict[str, Dict[str, float]] = {}
        for holder in self._gcs.metrics._node_dumps.values():
            for rec in holder["dump"]:
                name = rec.get("name", "")
                if not name.startswith("raytpu_serve_"):
                    continue
                short = name[len("raytpu_serve_"):]
                if rec.get("kind") == "histogram":
                    for key, _buckets, hsum, total in rec.get("hist", []):
                        tags = dict(map(tuple, key))
                        ent = lat.setdefault(tags.get("app", "-"), {})
                        if name == "raytpu_serve_phase_seconds":
                            slot = ent.setdefault("phases", {}).setdefault(
                                tags.get("phase", "?"), [0.0, 0])
                        elif name == "raytpu_serve_ttft_seconds":
                            slot = ent.setdefault("ttft", [0.0, 0])
                        elif name == "raytpu_serve_itl_seconds":
                            slot = ent.setdefault("itl", [0.0, 0])
                        else:
                            continue
                        slot[0] += hsum
                        slot[1] += total
                elif rec.get("kind") == "counter":
                    for key, value in rec.get("samples", []):
                        tags = dict(map(tuple, key))
                        dst = counters.setdefault(tags.get("app", "-"), {})
                        sub = tags.get("event") or tags.get("status")
                        k = f"{short}.{sub}" if sub else short
                        dst[k] = round(dst.get(k, 0.0) + float(value), 3)
        latency: Dict[str, dict] = {}
        for app, ent in lat.items():
            row: Dict[str, Any] = {}
            for field, label in (("ttft", "ttft_mean_s"),
                                 ("itl", "itl_mean_s")):
                s, c = ent.get(field, (0.0, 0))
                if c:
                    row[label] = round(s / c, 6)
            phases = {p: round(s / c, 6)
                      for p, (s, c) in ent.get("phases", {}).items() if c}
            if phases:
                row["phase_mean_s"] = phases
            if row:
                latency[app] = row
        return {"apps": self.merged(), "latency": latency,
                "counters": counters}


class TrainRunState:
    """Train-plane goodput aggregator (the read side of the train gauge
    federation): ranks push cumulative step/phase counters to their
    node daemons, the daemons' `train` state key rides syncer deltas
    here, and this manager folds them — per run — into a goodput split
    (productive compute vs data-stall vs sync-stall vs checkpoint vs
    lost-to-restart), a cross-rank skew window (p99/p50 step time,
    blame-rank attribution), and an optional MFU estimate from
    `ScalingConfig.flops_per_step`.

    Unlike ServeGauges this view is RETAINED: daemon-side gauges are
    TTL-swept, but a gang restart must not erase the dead attempt's
    accounting and a SIGSTOPped rank must stay attributable after it
    goes quiet — so every (rank, attempt) entry the syncer ever showed
    us is kept until the run itself is pruned."""

    # A rank whose last daemon push is older than this is stale: it
    # stopped making progress without dying (SIGSTOP, livelock) and
    # becomes the skew blame rank regardless of its last step window.
    STALE_RANK_S = 5.0
    # A run with no gauge traffic for this long is no longer "active"
    # (status lines, doctor); it stays queryable until pruned.
    ACTIVE_WINDOW_S = 15.0
    MAX_RUNS = 64

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs
        # run -> {"ranks": {"rank@attempt": {"g": gauges, "seen_ts"}},
        #         "first_seen", "last_seen"}
        self._runs: Dict[str, dict] = {}

    # -- ingest ----------------------------------------------------------

    def refresh(self) -> None:
        """Fold every alive node's synced `train` state into the
        retained per-run view (latest push per (rank, attempt) wins —
        steps are cumulative, so the bigger counter is newer)."""
        now = time.time()
        for n in self._gcs.nodes.view.alive_nodes():
            for run, ranks in (getattr(n, "train", None) or {}).items():
                ent = self._runs.setdefault(
                    run, {"ranks": {}, "first_seen": now, "last_seen": now})
                for key, g in (ranks or {}).items():
                    prev = ent["ranks"].get(key)
                    if (prev is not None
                            and g.get("steps", 0) < prev["g"].get("steps", 0)):
                        continue
                    seen = now - float(g.get("ts_age_s", 0.0) or 0.0)
                    ent["ranks"][key] = {"g": dict(g), "seen_ts": seen}
                    ent["last_seen"] = max(ent["last_seen"], seen)
        if len(self._runs) > self.MAX_RUNS:
            for run, _ in sorted(self._runs.items(),
                                 key=lambda kv: kv[1]["last_seen"])[
                                     :len(self._runs) - self.MAX_RUNS]:
                del self._runs[run]

    def _restart_events(self, run: str) -> List[dict]:
        return [e for e in self._gcs.event_log.list_events(source="train")
                if e.get("run") == run]

    # -- derivation ------------------------------------------------------

    def _summarize(self, run: str, ent: dict) -> dict:
        from ray_tpu.util.metrics import percentile

        now = time.time()
        ranks = ent["ranks"]
        # Cumulative attribution across every attempt ever seen.
        tot = {k: 0.0 for k in ("step_s", "data_wait_s", "compute_s",
                                "sync_s", "checkpoint_s", "other_s")}
        latest_attempt = max((r["g"].get("attempt", 0)
                              for r in ranks.values()), default=0)
        cur: Dict[int, dict] = {}
        for r in ranks.values():
            g = r["g"]
            for k in tot:
                tot[k] += float(g.get(k, 0.0) or 0.0)
            if g.get("attempt", 0) == latest_attempt:
                cur[int(g.get("rank", 0))] = r
        # Restart accounting: each gang-start event's gap stalled the
        # WHOLE gang, so the lost wall is gap * world — comparable to
        # the per-rank attributed sums it joins in the denominator.
        events = self._restart_events(run)
        restarts = sum(1 for e in events if e.get("gap_s", 0.0) > 0.0)
        lost_s = sum(float(e.get("gap_s", 0.0) or 0.0)
                     * max(1, int(e.get("world", 1) or 1)) for e in events)
        attributed = sum(tot.values()) - tot["step_s"]  # phases only
        denom = attributed + lost_s
        productive = tot["compute_s"] + tot["other_s"]
        split = {}
        goodput = None
        if denom > 0:
            split = {
                "compute": round(productive / denom, 4),
                "data_wait": round(tot["data_wait_s"] / denom, 4),
                "sync": round(tot["sync_s"] / denom, 4),
                "checkpoint": round(tot["checkpoint_s"] / denom, 4),
                "lost_restart": round(lost_s / denom, 4),
            }
            goodput = split["compute"]
        # Current-attempt step rate + cross-rank skew over the recent
        # step window. Lockstep data-parallel runs move at the slowest
        # rank's pace, so the run rate is the min across ranks.
        rates, window_means, stale_ranks = [], {}, []
        world = steps = 0
        run_id = None
        for rank, r in sorted(cur.items()):
            g = r["g"]
            run_id = g.get("run_id") or run_id
            world = max(world, int(g.get("world", 0) or 0))
            steps = max(steps, int(g.get("steps", 0) or 0))
            ws, wt = g.get("window_steps", 0), g.get("window_step_s", 0.0)
            if ws and wt:
                rates.append(ws / wt)
                window_means[rank] = wt / ws
            if now - r["seen_ts"] > self.STALE_RANK_S:
                stale_ranks.append(rank)
        step_rate = round(min(rates), 4) if rates else 0.0
        skew: Dict[str, Any] = {}
        if window_means:
            vals = list(window_means.values())
            p50 = percentile(vals, 50)
            p99 = percentile(vals, 99)
            blame = max(window_means, key=window_means.get)
            skew = {"p50_step_s": round(p50, 6),
                    "p99_step_s": round(p99, 6),
                    "ratio": round(p99 / p50, 3) if p50 > 0 else 0.0,
                    "blame_rank": blame}
        if stale_ranks:
            # A stopped rank cannot report a slow window — staleness IS
            # the straggler signal, and the stalest rank takes the blame.
            skew["stale_ranks"] = sorted(stale_ranks)
            skew["blame_rank"] = min(
                ((rank, cur[rank]["seen_ts"]) for rank in stale_ranks),
                key=lambda kv: kv[1])[0]
        out = {
            "run": run, "run_id": run_id, "attempt": latest_attempt,
            "world": world, "steps": steps,
            "active": (now - ent["last_seen"]) <= self.ACTIVE_WINDOW_S,
            "last_seen_age_s": round(now - ent["last_seen"], 1),
            "step_rate": step_rate,
            "restarts": restarts,
            "lost_restart_s": round(lost_s, 3),
            "attributed_s": {k: round(v, 3) for k, v in tot.items()},
            "split": split, "goodput": goodput, "skew": skew,
        }
        flops = next((r["g"].get("flops_per_step")
                      for r in cur.values()
                      if r["g"].get("flops_per_step")), None)
        if flops and step_rate:
            out["achieved_flops"] = flops * step_rate
            peak = get_config().train_obs_peak_flops
            if peak > 0:
                out["mfu"] = round(out["achieved_flops"] / peak, 4)
        return out

    # -- RPC surface (service "Train") -----------------------------------

    def summary(self) -> dict:
        """`ray-tpu train status` / cluster_status()["observability"]
        ["train"] payload: every retained run's goodput split, step
        rate, skew window and restart accounting."""
        self.refresh()
        return {"runs": {run: self._summarize(run, ent)
                         for run, ent in self._runs.items()}}

    def run_status(self, run: str) -> Optional[dict]:
        self.refresh()
        ent = self._runs.get(run)
        return self._summarize(run, ent) if ent else None


class DiagnosisManager:
    """Cluster-wide diagnosis fan-out (ISSUE 5 tentpole part 1; ref: the
    dashboard's per-node `ray stack`/CpuProfilingManager surfaces): one
    RPC here signals every matching daemon's workers for signal-safe
    all-thread stack dumps and returns the merged, parsed results —
    the `ray-tpu stack` backend."""

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs

    async def dump_stacks(self, node_id: Optional[str] = None,
                          worker_id: Optional[str] = None,
                          pids: Optional[List[int]] = None) -> List[dict]:
        """Fan `NodeDaemon.dump_worker_stacks` out over every alive
        (matching) daemon; a node that fails mid-dump reports its error
        instead of poisoning the rest."""
        nodes = [n for n in self._gcs.nodes.view.nodes.values()
                 if n.alive
                 and (not node_id or n.node_id.startswith(node_id))]

        async def one(n) -> dict:
            client = self._gcs.daemon_client(n.node_id)
            if client is None:
                return {"node_id": n.node_id, "workers": [],
                        "error": "daemon unreachable"}
            try:
                return await client.call(
                    "NodeDaemon", "dump_worker_stacks",
                    worker_id=worker_id, pids=pids, timeout=30)
            except Exception as e:  # noqa: BLE001
                return {"node_id": n.node_id, "workers": [],
                        "error": repr(e)}

        return list(await asyncio.gather(*(one(n) for n in nodes)))

    async def summarize_stacks(self, node_id: Optional[str] = None
                               ) -> dict:
        """dump_stacks + cross-worker grouping of identical stacks —
        the "412/512 workers blocked in all_reduce" answer in one RPC."""
        from ray_tpu.util.profiling import summarize_stacks

        results = await self.dump_stacks(node_id=node_id)
        return {"groups": summarize_stacks(results), "nodes": results}


class AutoscalerStateManager:
    """Autoscaler-facing cluster state (ref: GcsAutoscalerStateManager,
    src/ray/gcs/gcs_server/gcs_autoscaler_state_manager.h + the
    AutoscalerStateService in src/ray/protobuf/autoscaler.proto:315).

    Aggregates everything the autoscaler needs into one RPC:
      - per-node capacity / availability / queued task demand / idle time,
      - pending (unschedulable) actors and placement groups,
      - explicit `request_resources` targets (sdk parity).
    """

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs
        self._resource_requests: List[Dict[str, float]] = []

    def request_resources(self, bundles: List[Dict[str, float]]) -> dict:
        """Set (replace) the explicit min-capacity target, like
        ray.autoscaler.sdk.request_resources — the cluster should scale so
        these bundles *could* be placed; [] clears the request."""
        self._resource_requests = [dict(b) for b in bundles]
        return {"ok": True}

    def get_cluster_status(self) -> dict:
        now = time.monotonic()
        nodes = []
        for n in self._gcs.nodes.view.nodes.values():
            nodes.append({
                "node_id": n.node_id,
                "alive": n.alive,
                "total": dict(n.total),
                "available": dict(n.available),
                "queued_demand": [dict(d) for d in n.queued],
                "idle_s": max(0.0, now - n.last_busy) if n.alive else 0.0,
                "labels": dict(n.labels),
                # Synced through the delta channel (syncer.py): pool
                # depth + store pressure, for scale-down safety checks.
                "worker_pool": {"workers": n.workers,
                                "idle": n.idle_workers,
                                "busy": n.busy_workers},
                "store": {"used": n.store_used,
                          "objects": n.store_objects,
                          "spilled": n.spilled_bytes},
            })
        pending_actors = [
            dict(rec.demand) for rec in self._gcs.actors.actors.values()
            if rec.state in (ACTOR_PENDING, ACTOR_RESTARTING)
        ]
        pending_pgs = [
            {"bundles": [dict(b) for b in rec.bundles],
             "strategy": rec.strategy}
            for rec in self._gcs.placement_groups.groups.values()
            if rec.state == PG_PENDING
        ]
        return {
            "nodes": nodes,
            "pending_actors": pending_actors,
            "pending_pgs": pending_pgs,
            "resource_requests": [dict(b) for b in self._resource_requests],
        }


class LogManager:
    """Cluster log hub (ref: the log monitor → GCS pubsub → driver path,
    python/ray/_private/log_monitor.py + worker.py print_logs): node
    daemons ship tailed worker lines here; drivers subscribe to the
    ``logs`` pubsub channel; a per-worker ring buffer keeps the last
    lines of DEAD workers inspectable (dashboard/CLI `ray-tpu logs`)."""

    RING_LINES = 400

    def __init__(self, gcs: "GcsServer"):
        self._gcs = gcs
        # (node_id, worker_id, stream) -> deque[str]; insertion-ordered.
        self._rings: Dict[tuple, deque] = {}
        self._meta: Dict[tuple, dict] = {}

    def add_logs(self, records: List[dict]) -> int:
        for rec in records:
            key = (rec["node_id"], rec["worker_id"], rec["stream"])
            ring = self._rings.get(key)
            if ring is None:
                if len(self._rings) > 4000:  # oldest-worker eviction
                    old = next(iter(self._rings))
                    self._rings.pop(old, None)
                    self._meta.pop(old, None)
                ring = self._rings[key] = deque(maxlen=self.RING_LINES)
            ring.extend(rec["lines"])
            self._meta[key] = {"actor_id": rec.get("actor_id"),
                               "job_id": rec.get("job_id"),
                               "pid": rec.get("pid")}
            self._gcs.pubsub.publish("logs", rec)
        return len(records)

    def tail_logs(self, node_id: Optional[str] = None,
                  worker_id: Optional[str] = None,
                  actor_id: Optional[str] = None,
                  job_id: Optional[str] = None,
                  num_lines: int = 100) -> List[dict]:
        """Recent lines per matching worker stream (dead or alive)."""
        out = []
        for (nid, wid, stream), ring in self._rings.items():
            meta = self._meta.get((nid, wid, stream), {})
            if node_id and not nid.startswith(node_id):
                continue
            if worker_id and not wid.startswith(worker_id):
                continue
            if actor_id and not (meta.get("actor_id") or "").startswith(
                    actor_id):
                continue
            if job_id and meta.get("job_id") != job_id:
                continue
            out.append({"node_id": nid, "worker_id": wid, "stream": stream,
                        **meta, "lines": list(ring)[-num_lines:]})
        return out


class GcsServer:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 storage_dir: Optional[str] = None):
        from ray_tpu.core.distributed.gcs_storage import open_store

        # Durable backend (ref: gcs_storage knob, ray_config_def.h:402):
        # with a storage dir, KV/actors/PGs/jobs survive a GCS restart —
        # daemons re-register via heartbeats and detached actors keep
        # their names (the Redis-backed fault-tolerance story).
        from ray_tpu.core.distributed.syncer import ClusterSyncer

        self.store = open_store(storage_dir)
        # The GCS's own node identity: labels its process metrics in the
        # federated exposition and survives restarts when durable (the
        # journal a recovered GCS serves should carry the same label).
        import uuid

        meta = self.store.all("meta")
        self.node_id = meta.get("gcs_id") or uuid.uuid4().hex
        if meta.get("gcs_id") != self.node_id:
            self.store.put("meta", "gcs_id", self.node_id)
        self.pubsub = Pubsub()
        self.kv = KV(self.store)
        self.nodes = NodeInfo(self)
        # Versioned delta sync (syncer.py): merges per-node state pushes
        # into self.nodes.view and fans the coalesced cluster view back
        # out to daemons. Constructed right after NodeInfo — every other
        # manager reads the view it maintains.
        self.syncer = ClusterSyncer(self)
        self.actors = ActorManager(self, self.store)
        self.objects = ObjectDirectory(self)
        self.placement_groups = PlacementGroupManager(self, self.store)
        self.jobs = JobManager(self, self.store)
        # Bounded per-job task-event store (task_events.py GcsTaskManager;
        # replaces the old unbounded deque sink).
        from ray_tpu.core.distributed.task_events import GcsTaskManager

        self.task_events = GcsTaskManager()
        self.metrics = MetricsFederation(self)
        self.diagnosis = DiagnosisManager(self)
        self.serve_gauges = ServeGauges(self)
        self.train_runs = TrainRunState(self)
        self.event_log = EventLog()
        self.flight = FlightRecorder(self, self.store)
        self.event_log.flight = self.flight
        self.kv.flight = self.flight
        self.attribution = GcsLoadAttribution(self)
        # Event-loop audit state (filled by _audit_loop; read by
        # gcs_load()/doctor even when the audit is disabled).
        self.loop_audit: Dict[str, Any] = {
            "samples": 0, "lag_last_s": 0.0, "lag_max_s": 0.0,
            "backlog": 0}
        self.autoscaler_state = AutoscalerStateManager(self)
        self.logs = LogManager(self)
        self.server = RpcServer(host, port)
        if get_config().gcs_attribution_enabled:
            self.server.attribution_sink = self.attribution.sink
        self._daemon_clients: Dict[str, AsyncRpcClient] = {}
        self._tasks: List[asyncio.Task] = []

    def daemon_client(self, node_id: str) -> Optional[AsyncRpcClient]:
        n = self.nodes.view.nodes.get(node_id)
        if n is None or not n.alive:
            return None
        client = self._daemon_clients.get(node_id)
        if client is None or client.address != n.address:
            client = AsyncRpcClient(n.address)
            self._daemon_clients[node_id] = client
        return client

    async def start(self) -> int:
        for name, svc in [
            ("NodeInfo", self.nodes), ("KV", self.kv),
            ("ActorManager", self.actors), ("ObjectDirectory", self.objects),
            ("PlacementGroups", self.placement_groups),
            ("JobManager", self.jobs), ("TaskEvents", self.task_events),
            ("EventLog", self.event_log),
            ("AutoscalerState", self.autoscaler_state),
            ("Pubsub", self.pubsub),
            ("LogManager", self.logs),
            ("Syncer", self.syncer),
            ("Metrics", self.metrics),
            ("Diagnosis", self.diagnosis),
            ("Serve", self.serve_gauges),
            ("Train", self.train_runs),
            ("FlightRecorder", self.flight),
        ]:
            self.server.add_service(name, svc)
        port = await self.server.start()
        self._start_metrics_http()
        self._tasks = [
            asyncio.ensure_future(self.nodes.health_check_loop()),
            asyncio.ensure_future(self.actors.scheduling_loop()),
            asyncio.ensure_future(self.placement_groups.scheduling_loop()),
            asyncio.ensure_future(self.syncer.broadcast_loop()),
            asyncio.ensure_future(self._audit_loop()),
        ]
        self.flight.record("gcs.start", "GCS serving",
                           node_id=self.node_id,
                           fields={"address": self.server.address})
        # Resume scheduling of state loaded from durable storage.
        self.actors.requeue_loaded()
        self.placement_groups.requeue_loaded()
        logger.info("GCS listening on %s", self.server.address)
        return port

    async def _audit_loop(self) -> None:
        """GCS event-loop audit. The GCS runs on a plain asyncio.run
        loop (not an EventLoopThread), so it has no lag probe of its
        own: a timed sleep measures its overshoot — lag means some
        handler or import blocked the loop — and each tick also samples
        the asyncio task backlog and KV/journal sizes into gcs-labelled
        gauges that ride the federated exposition."""
        from ray_tpu.util.metrics import Gauge, process_sample

        interval = get_config().gcs_loop_audit_ms / 1000.0
        if interval <= 0:
            return
        g_lag = Gauge("raytpu_gcs_loop_lag_seconds",
                      "GCS event-loop lag (audit sleep overshoot)")
        g_backlog = Gauge("raytpu_gcs_loop_backlog",
                          "asyncio tasks pending on the GCS loop")
        g_kv = Gauge("raytpu_gcs_kv_keys",
                     "keys in the GCS KV store")
        g_flight = Gauge("raytpu_gcs_flight_events",
                         "entries in the cluster flight recorder")
        # The GCS's own process footprint, in the same registry the
        # federation labels with this GCS's node id: the control plane
        # monitors itself with the machinery it runs for everyone else.
        g_proc = {
            "rss_bytes": Gauge("raytpu_gcs_process_rss_bytes",
                               "GCS process resident set size"),
            "cpu_seconds": Gauge("raytpu_gcs_process_cpu_seconds",
                                 "GCS process cumulative CPU time"),
            "open_fds": Gauge("raytpu_gcs_process_open_fds",
                              "GCS process open file descriptors"),
            "threads": Gauge("raytpu_gcs_process_threads",
                             "GCS process live threads"),
        }
        audit = self.loop_audit
        while True:
            t0 = time.monotonic()
            await asyncio.sleep(interval)
            lag = max(0.0, time.monotonic() - t0 - interval)
            audit["samples"] += 1
            audit["lag_last_s"] = round(lag, 6)
            audit["lag_max_s"] = max(audit["lag_max_s"], round(lag, 6))
            audit["backlog"] = sum(1 for t in asyncio.all_tasks()
                                   if not t.done())
            g_lag.set(lag)
            g_backlog.set(audit["backlog"])
            g_kv.set(len(self.kv._data))
            g_flight.set(len(self.flight.events))
            for name, value in process_sample().items():
                g = g_proc.get(name)
                if g is not None:
                    g.set(value)

    def _start_metrics_http(self) -> None:
        """Federated /metrics on the GCS (ref: the dashboard's
        prometheus scrape target): one exposition covering every node's
        last syncer-shipped snapshot, node-labelled."""
        port = get_config().metrics_gcs_export_port
        if not port:
            return
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        gcs = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = gcs.metrics.federated_text().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        try:
            srv = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        except OSError as e:
            logger.warning("GCS metrics port %d unavailable: %s", port, e)
            return
        self._metrics_http = srv
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        logger.info("federated metrics on :%d/metrics",
                    srv.server_address[1])

    async def stop(self):
        srv = getattr(self, "_metrics_http", None)
        if srv is not None:
            srv.shutdown()
        for t in self._tasks:
            t.cancel()
        await self.server.stop()
        self.store.close()


def main():
    import argparse
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--storage-dir", default=None,
                        help="durable state dir (GCS fault tolerance)")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format="[gcs] %(asctime)s %(levelname)s %(message)s")
    from ray_tpu.core.distributed.driver import start_watch_parent_thread

    start_watch_parent_thread()

    async def run():
        gcs = GcsServer(args.host, args.port, storage_dir=args.storage_dir)
        port = await gcs.start()
        # Handshake: parent reads the bound port from stdout.
        print(f"GCS_PORT={port}", flush=True)
        await asyncio.Event().wait()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        sys.exit(0)


if __name__ == "__main__":
    main()
