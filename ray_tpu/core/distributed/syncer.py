"""Cluster-state syncer: versioned delta broadcast between daemons and GCS.

Analogue of the reference RaySyncer (ref: src/ray/protobuf/ray_syncer.proto:62
RaySyncerMessage{version, node_id, message_type, sync_message};
src/ray/common/ray_syncer/ray_syncer.h:88 — each node reports versioned
RESOURCE_VIEW / COMMANDS snapshots over a long-lived bidi stream, receivers
apply them idempotently by (node_id, version)). Before this subsystem every
daemon re-sent its whole resource dict on a poll-loop heartbeat and re-read
the whole node table at 1 Hz — O(nodes²) control-plane bytes that capped the
scale envelope at single-digit daemons (VERDICT "What's missing" #2; control
plane sync overhead is exactly what limits concurrency at pod scale,
arXiv:2011.03641).

Two halves:

  NodeSyncer     (daemon / virtual-node side): keeps a monotonically
                 versioned local view (resources, load, object-store stats,
                 worker-pool depth), diffs it against the last acknowledged
                 snapshot every coalescing interval, and pushes ONLY the
                 changed keys. Unchanged ticks are suppressed; an idle node
                 degrades to a tiny keepalive that piggybacks liveness on
                 the sync channel. On (re)connect — GCS restart, stale-node
                 verdict, version gap — it resyncs with one full snapshot.

  ClusterSyncer  (GCS side): merges per-node versions with sequence-numbered
                 idempotent apply (duplicates ignored, gaps answered with a
                 resync request), folds the result into NodeInfo's
                 ClusterView (the same object the scheduler and autoscaler
                 read), and fans a coalesced cluster view back out to
                 subscribed daemons over a server-streaming RPC — the
                 spillback view that used to be a 1 Hz full list_nodes poll.

Every knob is a `RAY_TPU_SYNCER_*` env var (config.py); both halves export
Prometheus counters for deltas sent/suppressed/bytes so the delta-vs-full
ratio is assertable (bench_scale many_nodes does exactly that).
"""
from __future__ import annotations

import asyncio
import logging
import pickle
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional

from ray_tpu.core.config import get_config
from ray_tpu.core.distributed.scheduler import (
    apply_node_wire,
    node_wire,
)

logger = logging.getLogger(__name__)

# State keys a node may report. Anything else in a push is dropped at
# apply time — the version seam (wire.py PROTOCOL_VERSION) covers real
# schema changes; this guard just keeps a buggy reporter from growing
# NodeView attributes dynamically.
STATE_KEYS = (
    "available",        # resources free right now
    "queued",           # queued lease demand (autoscaler input)
    "store_used",       # shm object-store bytes in use
    "store_objects",    # objects in the shm store
    "spilled_bytes",    # bytes spilled to disk
    "workers",          # worker-pool depth: live worker processes
    "idle_workers",     # ... of which idle (warm pool)
    "busy_workers",     # ... of which leased/actor-bound
    "serve",            # per-app serve replica gauges (autoscale input)
    "train",            # per-(run, rank) train step/phase gauges
)


class NodeSyncer:
    """Daemon-side reporter + cluster-view receiver.

    Transport-agnostic: `gcs` is anything with ``async call(service,
    method, **kw)`` and ``stream(service, method, **kw)`` (an
    AsyncRpcClient in production; tests pass fakes, and many virtual
    nodes share one multiplexed client).
    """

    def __init__(
        self,
        *,
        gcs: Any,
        node_id: str,
        collect: Callable[[], Dict[str, Any]],
        on_view: Optional[Callable[[dict], None]] = None,
        on_reregister: Optional[Callable[[], Awaitable[None]]] = None,
        report_interval_s: Optional[float] = None,
        keepalive_s: Optional[float] = None,
        metrics: Optional[dict] = None,
        metrics_provider: Optional[Callable[[], Any]] = None,
    ):
        cfg = get_config()
        self.gcs = gcs
        self.node_id = node_id
        self._collect = collect
        self._on_view = on_view
        self._on_reregister = on_reregister
        self.report_interval_s = (
            report_interval_s if report_interval_s is not None
            else cfg.syncer_report_interval_ms / 1000.0)
        self.keepalive_s = (keepalive_s if keepalive_s is not None
                            else cfg.syncer_keepalive_ms / 1000.0)
        # Metrics federation: a registry snapshot piggybacks on an
        # ordinary push (delta, full, OR keepalive — idle nodes must
        # stay fresh in the GCS's federated view) at a much slower
        # cadence than the delta interval. None/0 disables.
        self._metrics_provider = metrics_provider
        self.metrics_interval_s = cfg.metrics_sync_interval_ms / 1000.0
        self._last_metrics_t = 0.0
        # None => next push is a full snapshot (first contact / resync).
        self._last_sent: Optional[Dict[str, Any]] = None
        self.version = 0
        self._dirty = asyncio.Event()
        self._last_push_t = 0.0         # monotonic, successful pushes only
        self._last_view_t = 0.0         # monotonic, last broadcast applied
        self.view_version = 0           # cluster_version last applied
        # Prometheus counters are optional (the daemon passes its own,
        # node_id-tagged; 1000 in-process virtual nodes would collide on
        # the registry, so they rely on this dict instead).
        self._metrics = metrics or {}
        self.stats = {
            "deltas_sent": 0, "full_syncs": 0, "keepalives": 0,
            "suppressed": 0, "bytes_sent": 0, "errors": 0,
            "resyncs_requested": 0, "stale_verdicts": 0,
            "view_payloads": 0,
        }

    # -- public hooks ---------------------------------------------------
    def mark_dirty(self) -> None:
        """Hot-path hint (lease grant/return): wake the report loop now
        instead of at the next tick. Coalescing still applies — pushes
        never exceed 1/report_interval."""
        self._dirty.set()

    def force_full_resync(self) -> None:
        """Next push sends a full snapshot (re-registration, operator)."""
        self._last_sent = None

    def healthy(self) -> bool:
        """Did a push succeed recently enough that liveness is riding the
        sync channel? The heartbeat loop uses this to degrade itself to a
        slow fallback."""
        return (time.monotonic() - self._last_push_t
                < max(self.keepalive_s * 2, self.report_interval_s * 4))

    def view_fresh(self, max_age_s: float = 5.0) -> bool:
        """Has a broadcast been applied recently? The daemon's list_nodes
        poll loop only runs while this is False."""
        return time.monotonic() - self._last_view_t < max_age_s

    # -- report path ----------------------------------------------------
    async def sync_once(self) -> str:
        """One report cycle. Returns what happened: 'full' | 'delta' |
        'keepalive' | 'suppressed'. Raises on transport errors (the loop
        owns backoff)."""
        state = self._collect()
        now = time.monotonic()
        if self._last_sent is None:
            return await self._push(state, full=True)
        delta = {k: v for k, v in state.items()
                 if self._last_sent.get(k) != v}
        if not delta:
            if now - self._last_push_t >= self.keepalive_s:
                return await self._push(None, keepalive=True)
            self.stats["suppressed"] += 1
            self._inc("suppressed")
            return "suppressed"
        return await self._push(state, delta=delta)

    def _metrics_payload(self) -> Optional[Any]:
        """Registry snapshot to piggyback, when due (rate-limited to
        metrics_interval_s; never blocks or fails the push)."""
        if (self._metrics_provider is None
                or self.metrics_interval_s <= 0):
            return None
        now = time.monotonic()
        if now - self._last_metrics_t < self.metrics_interval_s:
            return None
        self._last_metrics_t = now
        try:
            return self._metrics_provider()
        except Exception:  # noqa: BLE001 telemetry must not break sync
            return None

    async def _push(self, state: Optional[Dict[str, Any]],
                    delta: Optional[Dict[str, Any]] = None,
                    full: bool = False, keepalive: bool = False) -> str:
        msnap = self._metrics_payload()
        # GCS load attribution: pushes are the syncer's own load, not
        # the daemon's scheduler default.
        whoami = (self.node_id, "syncer")
        if keepalive:
            reply = await self.gcs.call(
                "Syncer", "push_update", node_id=self.node_id,
                version=self.version, keepalive=True, metrics=msnap,
                _caller=whoami, timeout=10)
            kind = "keepalive"
        else:
            payload = dict(state) if full else delta
            base = self.version
            version = self.version + 1
            reply = await self.gcs.call(
                "Syncer", "push_update", node_id=self.node_id,
                version=version, base_version=base, state=payload,
                full=full, metrics=msnap, _caller=whoami, timeout=10)
            kind = "full" if full else "delta"
        if not reply.get("registered", True):
            # The GCS does not know us (restart) or marked us dead
            # (stale-node verdict): re-register, then resync fully.
            self.stats["stale_verdicts"] += 1
            self.force_full_resync()
            if self._on_reregister is not None:
                await self._on_reregister()
            return "stale"
        if reply.get("resync"):
            # Version gap (a delta we sent was lost, or the GCS restarted
            # between pushes): the next cycle sends a full snapshot.
            self.stats["resyncs_requested"] += 1
            self.force_full_resync()
            return "resync"
        self._last_push_t = time.monotonic()
        if keepalive:
            self.stats["keepalives"] += 1
            self._inc("keepalives")
            return kind
        self.version += 1
        self._last_sent = dict(state)
        nbytes = len(pickle.dumps(payload, protocol=5))
        self.stats["bytes_sent"] += nbytes
        self._inc("bytes", nbytes)
        if full:
            self.stats["full_syncs"] += 1
            self._inc("full_syncs")
        else:
            self.stats["deltas_sent"] += 1
            self._inc("deltas")
        return kind

    async def report_loop(self) -> None:
        backoff = self.report_interval_s
        while True:
            try:
                await asyncio.wait_for(self._dirty.wait(),
                                       timeout=self.report_interval_s)
                # Dirty wake: still honor the coalescing floor so a storm
                # of grants/returns batches into one delta per interval.
                gap = self.report_interval_s - (time.monotonic()
                                                - self._last_push_t)
                if gap > 0:
                    await asyncio.sleep(gap)
            except asyncio.TimeoutError:
                pass
            self._dirty.clear()
            try:
                await self.sync_once()
                backoff = self.report_interval_s
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                # GCS down/unreachable: capped exponential backoff, and
                # the next successful push after a gap resyncs anyway.
                self.stats["errors"] += 1
                logger.debug("syncer push failed: %s (retry in %.1fs)",
                             e, backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2,
                              get_config().heartbeat_backoff_cap_s)

    # -- receive path (cluster-view fan-out) ----------------------------
    def apply_view_payload(self, payload: dict, view) -> None:
        """Fold one broadcast payload into a ClusterView (the daemon's
        spillback view)."""
        apply_node_wire(view, payload)
        self.view_version = payload.get("cluster_version", self.view_version)
        self._last_view_t = time.monotonic()
        self.stats["view_payloads"] += 1
        if self._on_view is not None:
            self._on_view(payload)

    async def subscribe_loop(self, view) -> None:
        """Long-lived server-streaming subscription to the GCS's coalesced
        cluster view; reconnects with backoff across GCS restarts."""
        backoff = 0.2
        while True:
            try:
                stream = self.gcs.stream("Syncer", "stream_cluster_view",
                                         node_id=self.node_id)
                async for payload in stream:
                    self.apply_view_payload(payload, view)
                    backoff = 0.2
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                logger.debug("cluster-view stream lost: %s (retry in "
                             "%.1fs)", e, backoff)
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2,
                          get_config().heartbeat_backoff_cap_s)

    def _inc(self, key: str, n: float = 1) -> None:
        m = self._metrics.get(key)
        if m is not None:
            m.inc(n)


class ClusterSyncer:
    """GCS-side merge + fan-out (ref: RaySyncer's receiver half +
    gcs_resource_manager's UpdateFromResourceView). Registered as the
    `Syncer` RPC service on the GCS server."""

    def __init__(self, gcs):
        self._gcs = gcs
        # node_id -> last applied version. Absent => the node must full-
        # sync first (fresh registration, GCS restart, post-death).
        self.versions: Dict[str, int] = {}
        self.cluster_version = 0
        self._dirty: set = set()        # node_ids changed since last fan-out
        self._dead_dirty: set = set()   # deaths to announce
        self._wake = asyncio.Event()
        self._subs: Dict[int, asyncio.Queue] = {}
        self._sub_seq = 0
        self.stats_counters = {
            "applied_deltas": 0, "applied_full": 0, "keepalives": 0,
            "stale_ignored": 0, "resync_requests": 0,
            "stale_node_verdicts": 0, "broadcasts": 0,
            "broadcast_payload_nodes": 0, "dirty_marks": 0,
        }
        self._init_metrics()

    def _init_metrics(self) -> None:
        from ray_tpu.util.metrics import Counter, Gauge

        self._m_deltas = Counter(
            "raytpu_syncer_updates_applied_total",
            "Delta updates applied by the GCS syncer")
        self._m_full = Counter(
            "raytpu_syncer_full_syncs_total",
            "Full node-state snapshots applied by the GCS syncer")
        self._m_stale = Counter(
            "raytpu_syncer_stale_updates_total",
            "Duplicate/out-of-order updates ignored (idempotent apply)")
        self._m_resync = Counter(
            "raytpu_syncer_resync_requests_total",
            "Version gaps answered with a resync request")
        self._m_broadcasts = Counter(
            "raytpu_syncer_broadcasts_total",
            "Coalesced cluster-view fan-outs")
        self._m_subs = Gauge(
            "raytpu_syncer_subscribers",
            "Live cluster-view stream subscribers")

    # -- RPC surface ----------------------------------------------------
    def push_update(self, node_id: str, version: int,
                    base_version: int = 0,
                    state: Optional[Dict[str, Any]] = None,
                    full: bool = False, keepalive: bool = False,
                    metrics: Optional[Any] = None) -> dict:
        """Apply one node update. Sequence-numbered and idempotent:
        duplicates/out-of-order arrivals are ignored, gaps get a resync
        verdict, and every accepted message (keepalives included)
        refreshes the node's liveness — the stream IS the heartbeat.
        A piggybacked registry snapshot (`metrics`) feeds the GCS's
        federated exposition."""
        view = self._gcs.nodes.view
        n = view.nodes.get(node_id)
        if n is None:
            return {"registered": False,
                    "reason": "unknown node; register first"}
        if not n.alive:
            # Stale-node verdict (mirrors NodeInfo.heartbeat): a dead
            # node's pushes must not resurrect its entry silently.
            self.stats_counters["stale_node_verdicts"] += 1
            return {"registered": False, "stale": True,
                    "reason": f"node {node_id[:8]} is marked dead"}
        if metrics is not None:
            fed = getattr(self._gcs, "metrics", None)
            if fed is not None:
                fed.ingest(node_id, metrics)
        cur = self.versions.get(node_id)
        if keepalive:
            n.last_heartbeat = time.monotonic()
            self.stats_counters["keepalives"] += 1
            return {"ok": True, "applied": cur}
        if full:
            # A full snapshot is authoritative for its version; replaying
            # the same version is a no-op by value, so accept-and-apply
            # keeps the path idempotent under at-least-once retries.
            view.apply_state(node_id, {k: v for k, v in (state or {}).items()
                                       if k in STATE_KEYS})
            self.versions[node_id] = version
            self.stats_counters["applied_full"] += 1
            self._m_full.inc()
            self._mark_dirty(node_id)
            return {"ok": True, "applied": version}
        if cur is None or base_version != cur:
            if cur is not None and version <= cur:
                # Duplicate or reordered old delta: already applied.
                self.stats_counters["stale_ignored"] += 1
                self._m_stale.inc()
                return {"ok": True, "applied": cur}
            self.stats_counters["resync_requests"] += 1
            self._m_resync.inc()
            return {"ok": False, "resync": True, "applied": cur}
        view.apply_state(node_id, {k: v for k, v in (state or {}).items()
                                   if k in STATE_KEYS})
        self.versions[node_id] = version
        self.stats_counters["applied_deltas"] += 1
        self._m_deltas.inc()
        self._mark_dirty(node_id)
        return {"ok": True, "applied": version}

    async def stream_cluster_view(self, node_id: str = ""):
        """Server-streaming fan-out: a full snapshot on subscribe, then
        coalesced deltas as nodes change. A subscriber that falls behind
        (queue full) is healed with a fresh full snapshot instead of an
        unbounded backlog."""
        q: asyncio.Queue = asyncio.Queue(maxsize=64)
        self._sub_seq += 1
        sid = self._sub_seq
        self._subs[sid] = q
        self._m_subs.set(len(self._subs))
        try:
            yield self._full_payload()
            while True:
                yield await q.get()
        finally:
            self._subs.pop(sid, None)
            self._m_subs.set(len(self._subs))

    def stats(self) -> dict:
        """Counters for tests/benches — the delta-vs-full ratio here is
        the proof the control plane ships deltas, not full-state posts."""
        return {
            "cluster_version": self.cluster_version,
            "nodes_tracked": len(self.versions),
            "subscribers": len(self._subs),
            **self.stats_counters,
        }

    # -- NodeInfo integration -------------------------------------------
    def on_node_registered(self, node_id: str) -> None:
        """Fresh (re-)registration: the node must full-sync before deltas
        apply, and the fan-out must announce it."""
        self.versions.pop(node_id, None)
        self._mark_dirty(node_id)

    def on_node_heartbeat(self, node_id: str) -> None:
        """A legacy/fallback heartbeat applied state through NodeInfo
        directly; mark the node so the fan-out stays coherent."""
        self._mark_dirty(node_id)

    def on_node_dead(self, node_id: str) -> None:
        self.versions.pop(node_id, None)
        self._dead_dirty.add(node_id)
        self.cluster_version += 1
        self._wake.set()

    def _mark_dirty(self, node_id: str) -> None:
        self._dirty.add(node_id)
        self.stats_counters["dirty_marks"] += 1
        self.cluster_version += 1
        self._wake.set()

    # -- fan-out --------------------------------------------------------
    def _full_payload(self) -> dict:
        return {
            "cluster_version": self.cluster_version,
            "full": True,
            "nodes": {nid: node_wire(n)
                      for nid, n in self._gcs.nodes.view.nodes.items()},
            "dead": [],
        }

    def _delta_payload(self) -> Optional[dict]:
        dirty, self._dirty = self._dirty, set()
        dead, self._dead_dirty = self._dead_dirty, set()
        view = self._gcs.nodes.view
        nodes = {nid: node_wire(view.nodes[nid])
                 for nid in dirty if nid in view.nodes}
        if not nodes and not dead:
            return None
        return {"cluster_version": self.cluster_version, "full": False,
                "nodes": nodes, "dead": sorted(dead)}

    async def broadcast_loop(self) -> None:
        interval = get_config().syncer_broadcast_interval_ms / 1000.0
        while True:
            await self._wake.wait()
            # Coalescing window: everything that lands while we sleep
            # rides the same payload.
            await asyncio.sleep(interval)
            self._wake.clear()
            payload = self._delta_payload()
            if payload is None:
                continue
            self.stats_counters["broadcasts"] += 1
            self.stats_counters["broadcast_payload_nodes"] += len(
                payload["nodes"])
            self._m_broadcasts.inc()
            for q in list(self._subs.values()):
                try:
                    q.put_nowait(payload)
                except asyncio.QueueFull:
                    # Slow subscriber: drop its backlog, queue one full
                    # snapshot that supersedes everything it missed.
                    while not q.empty():
                        try:
                            q.get_nowait()
                        except asyncio.QueueEmpty:
                            break
                    try:
                        q.put_nowait(self._full_payload())
                    except asyncio.QueueFull:
                        pass


def collect_queued_demand(lease_waiters, infeasible_waits) -> List[dict]:
    """Shared shape for the queued-demand report (heartbeat fallback and
    syncer state use the same aggregation)."""
    queued = [dict(d) for (d, *_rest) in lease_waiters]
    queued.extend(dict(d) for d in infeasible_waits.values())
    return queued
