"""Per-node log monitor: tail worker log files, ship lines to the GCS.

Analogue of the reference's log monitor process
(ref: python/ray/_private/log_monitor.py:1 LogMonitor, spawned per node
at node.py:1042): worker stdout/stderr land in per-worker files under
the node's log dir; the monitor tails every file, batches new lines,
and ships them to the GCS LogManager, which fans them out over pubsub
to subscribed drivers (prefixed driver-side printing, like the
reference's ``log_to_driver``) and keeps a per-worker ring buffer so a
DEAD worker's last lines remain inspectable from the dashboard/CLI.

Runs inside the node daemon's event loop rather than as a separate
process: the tail sweep is a few stat/read syscalls per worker — not
worth a process boundary here.
"""
from __future__ import annotations

import asyncio
import logging
import os
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

MAX_LINE_BYTES = 16 * 1024          # longer lines are truncated
MAX_SWEEP_BYTES = 512 * 1024        # per sweep, per file (burst guard)
MAX_FILE_BYTES = 64 * 1024 * 1024   # live-file rotation threshold


class _Tail:
    """Incremental reader of one append-only log file."""

    def __init__(self, path: str):
        self.path = path
        self.pos = 0
        self.last_seen_size = -1
        self._partial = b""

    def read_new_lines(self) -> List[str]:
        """New complete lines since the last call. A burst larger than
        MAX_SWEEP_BYTES is read across SUCCESSIVE sweeps (pos only
        advances over bytes actually consumed) — never dropped."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size <= self.pos:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.pos)
                data = f.read(min(size - self.pos, MAX_SWEEP_BYTES))
        except OSError:
            return []
        self.pos += len(data)
        data = self._partial + data
        *lines, self._partial = data.split(b"\n")
        if len(self._partial) > MAX_LINE_BYTES:  # runaway unterminated line
            lines.append(self._partial)
            self._partial = b""
        return [ln[:MAX_LINE_BYTES].decode("utf-8", "replace")
                for ln in lines]


class LogMonitor:
    """Tails ``worker-<id>.out|.err`` files in `log_dir` and ships new
    lines to the GCS LogManager in one RPC per sweep."""

    RETIRE_GRACE_S = 2.0

    def __init__(self, log_dir: str, node_id: str,
                 worker_info: Callable[[str], Dict[str, Any]],
                 period_s: float = 0.25):
        self.log_dir = log_dir
        self.node_id = node_id
        self.worker_info = worker_info  # worker_id -> {actor_id, job_id, pid}
        self.period_s = period_s
        self._tails: Dict[str, _Tail] = {}
        # worker_id -> retire deadline; without eviction a churny daemon
        # stats every log file ever created on each sweep and the log dir
        # grows without bound.
        self._retired: Dict[str, float] = {}

    def retire(self, worker_id: str) -> None:
        """Worker exited: after a grace period for trailing writes, its
        files are tailed one last time, unlinked, and forgotten (the GCS
        ring buffer keeps the last lines)."""
        import time

        self._retired.setdefault(worker_id,
                                 time.monotonic() + self.RETIRE_GRACE_S)

    def _maybe_rotate(self, tail: _Tail) -> None:
        """Copytruncate-style rotation for LIVE workers: once the tailer
        has shipped everything and the file is huge, truncate it to zero
        (the worker's fd is O_APPEND, so its next write lands at the new
        EOF) — a steadily-printing long-lived actor must not fill the
        node's disk (ref: the reference's rotated session log files).

        Rotation only fires when the file was QUIET for a whole sweep
        (size unchanged since last look AND fully shipped): the writer
        holds no lock we can take, so truncating a file that is being
        appended to mid-check would silently drop the racing lines —
        waiting for an idle sweep shrinks that window to the instant
        between the final getsize and the truncate."""
        try:
            size = os.path.getsize(tail.path)
        except OSError:
            return
        quiet = size == tail.last_seen_size
        tail.last_seen_size = size
        # A steady printer is never quiet, so past DOUBLE the threshold
        # rotation is forced anyway — losing the handful of racing lines
        # beats filling the node's disk. (A writer outpacing the tailer's
        # 512KB/sweep read rate would fill the disk regardless.)
        if tail.pos >= size and (
                (size > MAX_FILE_BYTES and quiet)
                or size > 2 * MAX_FILE_BYTES):
            try:
                os.truncate(tail.path, 0)
                tail.pos = 0
                tail.last_seen_size = 0
            except OSError:
                pass

    def _reap_retired(self) -> None:
        """Runs AFTER the sweep shipped any remaining lines: unlink only
        files the tail has fully caught up with (lines are never lost —
        a still-draining burst postpones the reap to the next sweep)."""
        import time

        now = time.monotonic()
        for worker_id, deadline in list(self._retired.items()):
            if now < deadline:
                continue
            done = True
            for suffix in (".out", ".err"):
                name = f"worker-{worker_id}{suffix}"
                path = os.path.join(self.log_dir, name)
                tail = self._tails.get(name)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    self._tails.pop(name, None)
                    continue
                if tail is not None and tail.pos < size:
                    done = False  # sweep hasn't shipped everything yet
                    continue
                self._tails.pop(name, None)
                try:
                    os.unlink(path)
                except OSError:
                    pass
            if done:
                self._retired.pop(worker_id, None)

    def sweep(self) -> List[dict]:
        """One pass over the log dir; returns the records to publish."""
        try:
            names = os.listdir(self.log_dir)
        except OSError:
            return []
        records: List[dict] = []
        for name in names:
            if not (name.startswith("worker-")
                    and (name.endswith(".out") or name.endswith(".err"))):
                continue
            tail = self._tails.get(name)
            if tail is None:
                tail = self._tails[name] = _Tail(
                    os.path.join(self.log_dir, name))
            lines = tail.read_new_lines()
            self._maybe_rotate(tail)
            if not lines:
                continue
            worker_id = name[len("worker-"):-4]
            info = self.worker_info(worker_id) or {}
            records.append({
                "node_id": self.node_id,
                "worker_id": worker_id,
                "stream": "stderr" if name.endswith(".err") else "stdout",
                "actor_id": info.get("actor_id"),
                "job_id": info.get("job_id"),
                "pid": info.get("pid"),
                "lines": lines,
            })
        self._reap_retired()
        return records

    async def run(self, gcs_client) -> None:
        """Sweep-and-ship loop; `gcs_client` is an AsyncRpcClient to the
        GCS. Errors are absorbed (a GCS blip must not kill the tailer —
        positions advance only on successful file reads, and unshipped
        records are retried next sweep by NOT advancing... they are
        already read, so on failure they are re-queued locally)."""
        pending: List[dict] = []
        while True:
            # Adaptive cadence: a sweep stats every tailed file, so with
            # a 1k-worker warm pool (2k files) the base 0.25 s period
            # alone costs ~8k syscalls/s of the daemon's loop. Scale the
            # period with the tail count (0.25 s small, up to 2 s at 2k+
            # files) — log latency trades against control-plane CPU.
            period = min(2.0, max(self.period_s,
                                  len(self._tails) / 1000.0))
            await asyncio.sleep(period)
            try:
                pending.extend(self.sweep())
                if not pending:
                    continue
                if len(pending) > 500:  # GCS outage backstop
                    del pending[:250]
                batch, pending = pending, []
                try:
                    await gcs_client.call("LogManager", "add_logs",
                                          records=batch, timeout=10)
                except Exception:  # noqa: BLE001 — retry next sweep
                    pending = batch + pending
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                logger.debug("log monitor sweep failed: %s", e)


def format_log_prefix(rec: dict) -> str:
    """Driver-side prefix, reference-style ``(pid=…, ip=…)`` adapted to
    ids: ``(worker=ab12cd34, node=ef56)`` or the actor id when known."""
    who = (f"actor={rec['actor_id'][:8]}" if rec.get("actor_id")
           else f"worker={rec['worker_id'][:8]}")
    pid = f" pid={rec['pid']}" if rec.get("pid") else ""
    return f"({who}{pid}, node={rec['node_id'][:8]})"
