"""Resource sets and node resource accounting.

Analogue of the reference's scheduling resources (ref: src/ray/common/
scheduling/resource_set.h, cluster_resource_data.h). Resources are
name→float maps ("CPU", "TPU", "memory", custom labels, and gang resources
like "TPU-v5e-16-head" per the reference's slice-head pattern,
_private/accelerators/tpu.py:382).
"""
from __future__ import annotations

from typing import Dict, Optional

ResourceSet = Dict[str, float]

EPS = 1e-9


def fits(available: ResourceSet, demand: ResourceSet) -> bool:
    for k, v in demand.items():
        if v > EPS and available.get(k, 0.0) + EPS < v:
            return False
    return True


def feasible(total: ResourceSet, demand: ResourceSet) -> bool:
    """Could the demand EVER fit on a node with these total resources?"""
    return fits(total, demand)


def subtract(avail: ResourceSet, demand: ResourceSet) -> None:
    for k, v in demand.items():
        if v > EPS:
            avail[k] = avail.get(k, 0.0) - v


def add(avail: ResourceSet, demand: ResourceSet) -> None:
    for k, v in demand.items():
        if v > EPS:
            avail[k] = avail.get(k, 0.0) + v


def utilization(total: ResourceSet, available: ResourceSet,
                demand: Optional[ResourceSet] = None) -> float:
    """Critical-resource utilization in [0,1]: the max over resource types
    the demand cares about (all types if demand is None). Matches the
    reference's best-node scoring input (ref: policy/scheduling_options.h)."""
    worst = 0.0
    keys = demand.keys() if demand else total.keys()
    for k in keys:
        t = total.get(k, 0.0)
        if t <= EPS:
            continue
        used = t - available.get(k, 0.0)
        worst = max(worst, used / t)
    return worst


def detect_node_resources(num_cpus: Optional[float] = None,
                          num_tpus: Optional[float] = None,
                          memory: Optional[int] = None,
                          custom: Optional[ResourceSet] = None) -> ResourceSet:
    """Autodetect this host's resources (TPU chips via jax when present —
    the analogue of the reference's TPUAcceleratorManager autodetection,
    ref: _private/accelerators/tpu.py:52-230 which reads GCE/GKE metadata)."""
    import os

    res: ResourceSet = {}
    res["CPU"] = float(num_cpus if num_cpus is not None
                       else (os.cpu_count() or 1))
    if num_tpus is not None:
        res["TPU"] = float(num_tpus)
    else:
        try:
            import jax

            tpus = [d for d in jax.devices() if d.platform in ("tpu", "axon")]
            if tpus:
                res["TPU"] = float(len(tpus))
        except Exception:
            pass
    if memory is None:
        try:
            import psutil

            memory = int(psutil.virtual_memory().total * 0.7)
        except Exception:
            memory = 8 << 30
    res["memory"] = float(memory)
    if custom:
        res.update(custom)
    return res
