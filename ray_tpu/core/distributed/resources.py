"""Resource sets and node resource accounting.

Analogue of the reference's scheduling resources (ref: src/ray/common/
scheduling/resource_set.h, cluster_resource_data.h). Resources are
name→float maps ("CPU", "TPU", "memory", custom labels, and gang resources
like "TPU-v5e-16-head" per the reference's slice-head pattern,
_private/accelerators/tpu.py:382).
"""
from __future__ import annotations

from typing import Dict, Optional

ResourceSet = Dict[str, float]

EPS = 1e-9


def fits(available: ResourceSet, demand: ResourceSet) -> bool:
    for k, v in demand.items():
        if v > EPS and available.get(k, 0.0) + EPS < v:
            return False
    return True


def feasible(total: ResourceSet, demand: ResourceSet) -> bool:
    """Could the demand EVER fit on a node with these total resources?"""
    return fits(total, demand)


def subtract(avail: ResourceSet, demand: ResourceSet) -> None:
    for k, v in demand.items():
        if v > EPS:
            avail[k] = avail.get(k, 0.0) - v


def add(avail: ResourceSet, demand: ResourceSet) -> None:
    for k, v in demand.items():
        if v > EPS:
            avail[k] = avail.get(k, 0.0) + v


def utilization(total: ResourceSet, available: ResourceSet,
                demand: Optional[ResourceSet] = None) -> float:
    """Critical-resource utilization in [0,1]: the max over resource types
    the demand cares about (all types if demand is None). Matches the
    reference's best-node scoring input (ref: policy/scheduling_options.h)."""
    worst = 0.0
    keys = demand.keys() if demand else total.keys()
    for k in keys:
        t = total.get(k, 0.0)
        if t <= EPS:
            continue
        used = t - available.get(k, 0.0)
        worst = max(worst, used / t)
    return worst


_tpu_probe_cache: Optional[int] = None


def run_tpu_probe(timeout_s: float, compute: bool = False
                  ) -> "tuple[int, str]":
    """Time-boxed subprocess probe: (tpu_chip_count, diagnostics).

    Shared by node-resource detection and bench.py. `compute=True` also
    runs a tiny jit'd add so a wedged-but-enumerable backend is caught.
    """
    import subprocess
    import sys

    code = (
        "import jax\n"
        "n = sum(1 for d in jax.devices() if d.platform in ('tpu','axon'))\n"
    )
    if compute:
        code += ("import jax.numpy as jnp\n"
                 "assert float(jnp.ones(()) + 1) == 2.0\n")
    code += "print('TPUCOUNT=%d' % n)\n"
    try:
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout_s)
        for line in out.stdout.splitlines():
            if line.startswith("TPUCOUNT="):
                return int(line.split("=", 1)[1]), out.stdout.strip()
        return 0, (out.stderr or out.stdout).strip()[-500:]
    except subprocess.TimeoutExpired:
        return 0, f"probe timed out after {timeout_s}s (backend init hang)"
    except (OSError, ValueError) as e:
        return 0, f"probe failed: {e}"


def probe_tpu_count(timeout_s: Optional[float] = None) -> int:
    """Count local TPU chips WITHOUT ever blocking the caller.

    The reference autodetects chips from GCE metadata / GKE env vars
    (ref: _private/accelerators/tpu.py:52-230) — a bounded read. Our
    equivalent has to go through jax backend init, which can hang
    indefinitely when the TPU runtime/tunnel is unhealthy, so the probe
    runs `jax.devices()` in a *time-boxed subprocess*: on timeout or
    error the answer is 0 and the control plane stays alive (a daemon
    that deadlocks on accelerator detection is not shippable).

    Overrides (checked in order):
      - RAY_TPU_NUM_TPUS: trust the operator, skip probing.
      - RAY_TPU_DISABLE_TPU_DETECTION=1: always 0.
      - JAX_PLATFORMS=cpu in our env: always 0 (test/CI mode).
    """
    global _tpu_probe_cache
    import os

    # lint: allow-knob -- detection override monkeypatched by tests mid-process; must stay dynamic
    forced = os.environ.get("RAY_TPU_NUM_TPUS")
    if forced is not None:
        return int(float(forced))
    # lint: allow-knob -- the autoscaler exports this into child envs; must stay dynamic
    if os.environ.get("RAY_TPU_DISABLE_TPU_DETECTION", "").lower() in (
            "1", "true", "yes"):
        return 0
    if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
        return 0
    if _tpu_probe_cache is not None:
        return _tpu_probe_cache
    if timeout_s is None:
        # lint: allow-knob -- probe timeout read alongside the dynamic detection overrides above
        timeout_s = float(os.environ.get("RAY_TPU_TPU_DETECT_TIMEOUT_S", "30"))

    count, _ = run_tpu_probe(timeout_s)
    _tpu_probe_cache = count
    return count


def detect_node_resources(num_cpus: Optional[float] = None,
                          num_tpus: Optional[float] = None,
                          memory: Optional[int] = None,
                          custom: Optional[ResourceSet] = None) -> ResourceSet:
    """Autodetect this host's resources (TPU chips via a time-boxed probe —
    the analogue of the reference's TPUAcceleratorManager autodetection,
    ref: _private/accelerators/tpu.py:52-230 which reads GCE/GKE metadata)."""
    import os

    res: ResourceSet = {}
    res["CPU"] = float(num_cpus if num_cpus is not None
                       else (os.cpu_count() or 1))
    n = float(num_tpus) if num_tpus is not None else float(probe_tpu_count())
    if n > 0:
        res["TPU"] = n
        # Slice-gang resources (TPU-{pod_type}-head etc.) attach whenever
        # the node has chips — explicit counts included, so operators who
        # pass --num-tpus on a GKE slice still get gang scheduling.
        try:
            from ray_tpu.core.distributed.accelerators import (
                tpu_extra_resources)

            res.update(tpu_extra_resources(int(n)))
        except Exception:
            pass
    if memory is None:
        try:
            import psutil

            memory = int(psutil.virtual_memory().total * 0.7)
        except Exception:
            memory = 8 << 30
    res["memory"] = float(memory)
    if custom:
        res.update(custom)
    return res
