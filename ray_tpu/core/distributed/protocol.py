"""Wire-format helpers shared by driver, daemon, and workers.

The task-spec/args framing analogue of the reference's TaskSpecification
protobuf (ref: src/ray/protobuf/common.proto TaskSpec) — here plain dicts
pickled by the RPC layer, with ObjectRef args replaced by resolvable markers
(inline small values ride in the spec itself, like the reference's inline
direct-call objects ≤ max_direct_call_object_size).
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import cloudpickle

from ray_tpu.core import serialization
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_ref import ObjectRef


class RefMarker:
    """Placeholder for a top-level ObjectRef argument. Carries the
    ref's owner address so the executing worker can fetch small values
    straight from the owner's inline cache (OwnerService) when the
    directory has no store copy."""

    __slots__ = ("oid_binary", "owner")

    def __init__(self, oid_binary: bytes, owner: Optional[str] = None):
        self.oid_binary = oid_binary
        self.owner = owner


def function_key(func_or_cls) -> bytes:
    """Content hash of the pickled function/class; the function-table key
    (ref: python/ray/_private/function_manager.py export-by-hash)."""
    blob = cloudpickle.dumps(func_or_cls, protocol=5)
    return hashlib.sha1(blob).digest(), blob


# Eager (not lazy): deterministic across processes, so the unpack fast
# path works in workers that never packed a no-arg call themselves.
_EMPTY_ARGS_BLOB: bytes = serialization.dumps(([], {}))
_EMPTY_DEPS: List[bytes] = []


def pack_args(args: List[Any], kwargs: Dict[str, Any],
              promote) -> Tuple[bytes, List[bytes]]:
    """Serialize (args, kwargs) replacing top-level ObjectRefs with markers.

    `promote(ref)` must guarantee the ref's value is readable from the shm
    store / directory by the executing worker. Returns (blob, dep_oids).

    No-arg calls (the dominant shape on actor hot paths) reuse one cached
    blob — zero serialization work per call.
    """
    if not args and not kwargs:
        return _EMPTY_ARGS_BLOB, _EMPTY_DEPS

    deps: List[bytes] = []

    def conv(v):
        if isinstance(v, ObjectRef):
            promote(v)
            deps.append(v.id().binary())
            return RefMarker(v.id().binary(), v.owner_address)
        return v

    packed = ([conv(a) for a in args],
              {k: conv(v) for k, v in kwargs.items()})
    return serialization.dumps(packed), deps


def unpack_args(blob: bytes, fetch) -> Tuple[List[Any], Dict[str, Any]]:
    """Deserialize an args blob, resolving RefMarkers via
    `fetch(oid, owner_address)`."""
    # No-arg fast path mirroring pack_args' cached blob: the dominant
    # actor/task hot-path shape skips deserialization entirely.
    if blob == _EMPTY_ARGS_BLOB:
        return [], {}
    args, kwargs = serialization.deserialize(blob)

    def conv(v):
        if isinstance(v, RefMarker):
            return fetch(ObjectID(v.oid_binary),
                         getattr(v, "owner", None))
        return v

    return [conv(a) for a in args], {k: conv(v) for k, v in kwargs.items()}


class TaskResult(NamedTuple):
    """One task return on the wire. NamedTuple, not dataclass: replies
    carry one per return value at tens of thousands per second, and a
    NamedTuple pickles as a bare args tuple (a dataclass drags a full
    __dict__ state round-trip)."""

    oid: bytes
    size: int
    inline: Optional[bytes] = None   # full framed payload if small
    is_error: bool = False


def make_task_spec(
    *,
    task_id: bytes,
    fn_key: bytes,
    args_blob: bytes,
    num_returns: int,
    caller_address: str,
    job_id: str,
    options: Dict[str, Any],
    actor_id: Optional[bytes] = None,
    method_name: str = "",
    seq: int = -1,
    attempt: int = 0,
) -> Dict[str, Any]:
    return {
        "task_id": task_id,
        "fn_key": fn_key,
        "args_blob": args_blob,
        "num_returns": num_returns,
        "caller_address": caller_address,
        "job_id": job_id,
        "options": options,
        "actor_id": actor_id,
        "method_name": method_name,
        "seq": seq,
        "attempt": attempt,
    }
