"""Bulk object transfer engine: striped windowed pulls + broadcast trees.

The data-plane counterpart of the control-plane RPC layer (rpc.py raw
frames). Role parity with the reference's object manager transfer
machinery (ref: src/ray/object_manager/object_manager.h:117 chunked
pull/push, pull_manager.h:52 in-flight budget, push_manager.h:30
bounded pushes) plus the 1→N pre-staging shape its collective-ish
`ray.experimental` broadcast utilities cover:

* `ChunkSink` — a create-then-fill receive surface over the store's
  PartialBuffer: chunks land at offsets directly in the shm mmap (any
  order, write-once ranges), an interval set tracks coverage, and the
  object seals itself the moment the last byte arrives. Waiters
  (`wait_range`) let a daemon RE-SERVE ranges of an in-flight object —
  the mechanism broadcast relays pipeline on.
* `striped_pull` — one object fetched chunk-wise from ALL known
  replicas at once under a bytes-based in-flight window. A source that
  errors is demoted immediately: its outstanding chunks requeue onto
  the surviving sources, so a node dying mid-transfer costs only its
  in-flight window, never a restart.
* `plan_broadcast_tree` — split a target list into ≤fanout subtrees
  for the log-N relay tree (node_daemon.broadcast_object), keeping the
  owner's uplink at fanout×size instead of N×size.

Everything here is asyncio-side: call it from the process's RPC loop.
"""
from __future__ import annotations

import asyncio
import bisect
import time
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ray_tpu.core.object_store import ObjectExistsError


def chunk_ranges(total_size: int, chunk_bytes: int
                 ) -> List[Tuple[int, int]]:
    """(offset, length) grid covering [0, total_size)."""
    if total_size <= 0:
        return []
    chunk_bytes = max(1, chunk_bytes)
    return [(off, min(chunk_bytes, total_size - off))
            for off in range(0, total_size, chunk_bytes)]


class IntervalSet:
    """Disjoint sorted [start, end) intervals with merge-on-add.

    Small by construction — transfers add chunk-grid ranges, so the set
    holds at most (in-flight window / chunk size) fragments before they
    coalesce.
    """

    def __init__(self):
        self._starts: List[int] = []
        self._ends: List[int] = []
        self.covered = 0

    def add(self, start: int, end: int) -> None:
        if end <= start:
            return
        i = bisect.bisect_left(self._ends, start)
        j = bisect.bisect_right(self._starts, end)
        if i < j:  # overlaps/touches intervals [i, j)
            start = min(start, self._starts[i])
            end = max(end, self._ends[j - 1])
            removed = sum(self._ends[k] - self._starts[k]
                          for k in range(i, j))
            del self._starts[i:j]
            del self._ends[i:j]
            self.covered -= removed
        self._starts.insert(i, start)
        self._ends.insert(i, end)
        self.covered += end - start

    def has(self, start: int, end: int) -> bool:
        if end <= start:
            return True
        i = bisect.bisect_right(self._starts, start) - 1
        return i >= 0 and self._ends[i] >= end


class ChunkSink:
    """Offset-addressed receive surface for one in-flight object.

    Wraps a store PartialBuffer; auto-seals when coverage completes.
    `wait_range`/`read` let concurrent consumers (broadcast children
    pulling from this daemon) stream ranges out while later ranges are
    still arriving.
    """

    def __init__(self, partial, total_size: int,
                 on_complete: Optional[Callable[[], None]] = None):
        self._pb = partial
        self.size = total_size
        self._have = IntervalSet()
        self._event = asyncio.Event()
        self.sealed = False
        self.aborted = False
        self.last_touch = time.monotonic()
        self._on_complete = on_complete
        if total_size == 0:
            self._seal()

    def _seal(self) -> None:
        self._pb.seal()
        self.sealed = True
        if self._on_complete is not None:
            self._on_complete()

    def write(self, offset: int, data) -> bool:
        """Land one chunk; returns True when this write completed (and
        sealed) the object. Ranges are write-once by protocol; a
        duplicate (retried chunk) is harmlessly overwritten with
        identical bytes."""
        if self.sealed or self.aborted:
            return self.sealed
        self._pb.write_at(offset, data)
        return self.commit(offset, len(data))

    def view_for(self, offset: int, length: int) -> memoryview:
        """Writable destination slice for a write-through receive
        (socket recv_into straight into the store mmap — the single-
        copy path). Pair with commit() once the bytes landed."""
        if offset < 0 or offset + length > self.size:
            raise ValueError(
                f"range [{offset}, {offset + length}) outside object "
                f"of {self.size} bytes")
        return self._pb.view[offset:offset + length]

    def commit(self, offset: int, length: int) -> bool:
        """Mark a range as landed (bytes already written via write() or
        through a view_for() slice); seals at full coverage."""
        if self.sealed or self.aborted:
            return self.sealed
        self._have.add(offset, offset + length)
        self.last_touch = time.monotonic()
        if self._have.covered >= self.size:
            self._seal()
        ev, self._event = self._event, asyncio.Event()
        ev.set()
        return self.sealed

    def has(self, offset: int, end: int) -> bool:
        return self.sealed or self._have.has(offset, end)

    async def wait_range(self, offset: int, end: int,
                         timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while not self.has(offset, end):
            if self.aborted:
                return False
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            ev = self._event
            try:
                await asyncio.wait_for(ev.wait(), remaining)
            except asyncio.TimeoutError:
                return False
        return True

    def read(self, offset: int, end: int) -> memoryview:
        """Zero-copy view of an already-landed range. Only valid while
        unsealed (the mapping closes at seal; sealed objects re-serve
        from the store). The returned slice keeps the mmap alive even
        across a concurrent seal — write-once ranges never mutate."""
        return self._pb.view[offset:end]

    def abort(self) -> None:
        if self.sealed or self.aborted:
            return
        self.aborted = True
        self._pb.abort()
        ev, self._event = self._event, asyncio.Event()
        ev.set()


# fetch_chunk(address, oid_b, offset, length, dest=None)
#   -> None (holder answered "missing") | (total_size, chunk_data).
# When `dest` (a writable memoryview) is given, a fetcher MAY receive
# the body straight into it (recv_into: kernel -> shm, one copy) and
# return (total_size, None); returning (total_size, data) instead means
# the engine copies via sink.write.
FetchChunkFn = Callable[..., Awaitable[Optional[Tuple[int, Any]]]]
# open_sink(oid_b, total_size) -> ChunkSink (raises ObjectExistsError
# when the object raced into the local store by other means)
OpenSinkFn = Callable[[bytes, int], ChunkSink]


async def striped_pull(
    oid_b: bytes,
    sources: List[Tuple[str, str]],          # (node_id, address)
    fetch_chunk: FetchChunkFn,
    open_sink: OpenSinkFn,
    *,
    chunk_bytes: int,
    window_bytes: int,
    per_source: int = 2,
    metrics: Optional[Dict[str, Any]] = None,
) -> Tuple[Optional[int], List[str]]:
    """Pull one object into the local store, striping chunk fetches
    across every source under a bytes-based in-flight window.

    Returns (total_size, stale_node_ids); total_size is None when no
    source produced the object. A source whose fetch raises is demoted
    for the rest of this transfer (its outstanding chunks requeue); a
    source that answers "missing" is reported stale so the caller can
    prune the directory entry.
    """
    stale: List[str] = []
    alive: List[Tuple[str, str]] = list(sources)
    inflight_gauge = metrics.get("inflight") if metrics else None
    bytes_in = metrics.get("bytes_in") if metrics else None
    gbps_hist = metrics.get("gbps") if metrics else None
    t_start = time.monotonic()

    # Phase 1: first chunk from the first usable source teaches us the
    # object's true size (the directory size is a hint).
    first: Optional[Tuple[int, Any]] = None
    while alive and first is None:
        node_id, addr = alive[0]
        try:
            first = await fetch_chunk(addr, oid_b, 0, chunk_bytes)
        except Exception:  # noqa: BLE001 — unreachable: demote
            alive.pop(0)
            continue
        if first is None:
            stale.append(node_id)
            alive.pop(0)
    if first is None:
        return None, stale
    total_size, data0 = first
    try:
        sink = open_sink(oid_b, total_size)
    except ObjectExistsError:
        return total_size, stale  # raced into the local store already
    pending: Dict[asyncio.Task, Tuple[int, int, Tuple[str, str]]] = {}
    inflight_bytes = 0
    try:
        sink.write(0, data0)
        remaining = [r for r in chunk_ranges(total_size, chunk_bytes)
                     if r[0] != 0]
        remaining.reverse()   # list-as-stack: pop() walks forward
        src_load: Dict[str, int] = {}
        rr = 0
        while remaining or pending:
            # Admit fetches up to the window, round-robin over sources
            # that still have per-source pipeline capacity.
            while remaining and alive:
                ready = [s for s in alive
                         if src_load.get(s[1], 0) < max(1, per_source)]
                if not ready:
                    break
                off, ln = remaining[-1]
                if pending and inflight_bytes + ln > window_bytes:
                    break
                remaining.pop()
                src = ready[rr % len(ready)]
                rr += 1
                task = asyncio.ensure_future(
                    fetch_chunk(src[1], oid_b, off, ln,
                                sink.view_for(off, ln)))
                pending[task] = (off, ln, src)
                src_load[src[1]] = src_load.get(src[1], 0) + 1
                inflight_bytes += ln
                if inflight_gauge is not None:
                    inflight_gauge.inc(ln)
            if not pending:
                # Chunks left but every source demoted/stale: give up.
                sink.abort()
                return None, stale
            done, _ = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for task in done:
                off, ln, (node_id, addr) = pending.pop(task)
                src_load[addr] -= 1
                inflight_bytes -= ln
                if inflight_gauge is not None:
                    inflight_gauge.dec(ln)
                try:
                    res = task.result()
                except Exception:  # noqa: BLE001 source died mid-pull:
                    # demote it; only ITS outstanding window requeues.
                    alive = [s for s in alive if s[1] != addr]
                    remaining.append((off, ln))
                    continue
                if res is None:
                    stale.append(node_id)
                    alive = [s for s in alive if s[1] != addr]
                    remaining.append((off, ln))
                    continue
                _, data = res
                if data is None:
                    sink.commit(off, ln)   # landed via recv_into dest
                else:
                    sink.write(off, data)
                if bytes_in is not None:
                    bytes_in.inc(ln)
        if not sink.sealed:  # defensive: coverage should have sealed it
            sink.abort()
            return None, stale
        if gbps_hist is not None and total_size:
            elapsed = max(time.monotonic() - t_start, 1e-9)
            gbps_hist.observe(total_size / elapsed / 1e9)
        return total_size, stale
    except BaseException:
        for task in list(pending):
            task.cancel()
        if inflight_gauge is not None and inflight_bytes:
            inflight_gauge.dec(inflight_bytes)
        sink.abort()
        raise


class _RawConn:
    """One blocking socket running one chunk request at a time, with a
    recv_into receive path: the raw-frame body goes from the kernel
    straight into the caller's destination buffer (the store mmap) —
    no StreamReader buffer, no intermediate bytes object."""

    def __init__(self, address: str, timeout: float):
        import socket as _socket

        host, port = address.rsplit(":", 1)
        self.sock = _socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self.sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._req_id = 0

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def _recv_exact(self, n: int) -> bytes:
        buf = bytearray(n)
        self._recv_into_exact(memoryview(buf))
        return bytes(buf)

    def _recv_into_exact(self, view: memoryview) -> None:
        got = 0
        n = len(view)
        while got < n:
            r = self.sock.recv_into(view[got:])
            if not r:
                raise ConnectionError("peer closed mid-frame")
            got += r

    def fetch_into(self, oid_b: bytes, offset: int, length: int,
                   dest: Optional[memoryview]
                   ) -> Optional[Tuple[int, Any]]:
        """One get_object_chunk round trip. Raw replies land their body
        in `dest` (returning (total_size, None)) or, when dest is absent
        or too small, in a fresh buffer (returning (total_size, data)).
        Returns None when the holder answered "missing"."""
        import struct as _struct

        from ray_tpu.core.distributed import rpc as _rpc
        from ray_tpu.core.distributed import wire as _wire

        self._req_id += 1
        payload = _rpc._ser(("NodeDaemon", "get_object_chunk",
                             {"object_id": oid_b, "offset": offset,
                              "length": length}))
        self.sock.sendall(_rpc._frame(_rpc.REQ, self._req_id, payload))
        head = self._recv_exact(_rpc._HEADER.size)
        flen, version, ftype, req_id = _rpc._HEADER.unpack(head)
        if version != _wire.PROTOCOL_VERSION:
            raise _rpc.ProtocolVersionError(version, req_id)
        if (ftype != _rpc.RES or req_id != self._req_id
                or flen < _rpc._POST_LEN + 1 or flen > _rpc.MAX_FRAME):
            raise _rpc.RpcError(
                f"unexpected frame (type {ftype}, len {flen}) on a "
                f"chunk connection")
        plen = flen - _rpc._POST_LEN
        codec = self._recv_exact(1)[0]
        plen -= 1
        if codec != _wire.CODEC_RAW:
            # Small control reply: "missing", or an error to surface.
            rest = self._recv_exact(plen)
            reply = _rpc._de(bytes([codec]) + rest)
            if not reply.get("ok"):
                raise _rpc._as_exception(reply.get("error"))
            result = reply.get("result") or {}
            if result.get("missing"):
                return None
            data = result.get("data")
            return result.get("total_size", len(data or b"")), data
        (hlen,) = _struct.unpack("<I", self._recv_exact(4))
        plen -= 4
        if hlen > plen:
            raise _rpc.RpcError("corrupt raw frame header")
        header = self._recv_exact(hlen)
        body_len = plen - hlen
        reply = _wire.raw_header_loads(header)
        if not reply.get("ok"):
            # Drain the body (error replies should not carry one).
            if body_len:
                self._recv_exact(body_len)
            raise _rpc._as_exception(reply.get("error"))
        result = reply["result"]
        total_size = result["total_size"]
        if dest is not None and len(dest) >= body_len:
            self._recv_into_exact(dest[:body_len])
            return total_size, None
        data = bytearray(body_len)
        self._recv_into_exact(memoryview(data))
        return total_size, data


class RawChunkFetcher:
    """striped_pull's default fetch backend: a per-peer pool of blocking
    raw-chunk sockets driven on executor threads. recv_into writes each
    chunk body from the kernel directly into the store mmap, and the
    GIL is released for the whole receive — the event loop keeps
    scheduling while bytes land."""

    POOL_PER_PEER = 8

    def __init__(self, timeout_s: Optional[float] = None):
        import threading

        self._timeout_s = timeout_s
        self._pools: Dict[str, List[_RawConn]] = {}
        self._lock = threading.Lock()
        self._closed = False

    def _timeout(self) -> float:
        if self._timeout_s is not None:
            return self._timeout_s
        from ray_tpu.core.config import get_config

        return get_config().transfer_chunk_timeout_s

    def _fetch_blocking(self, address: str, oid_b: bytes, offset: int,
                        length: int, dest) -> Optional[Tuple[int, Any]]:
        with self._lock:
            pool = self._pools.setdefault(address, [])
            conn = pool.pop() if pool else None
        if conn is None:
            conn = _RawConn(address, self._timeout())
        try:
            res = conn.fetch_into(oid_b, offset, length, dest)
        except BaseException:
            conn.close()    # unknown socket state: never repool
            raise
        with self._lock:
            pool = self._pools.setdefault(address, [])
            if self._closed or len(pool) >= self.POOL_PER_PEER:
                conn.close()
            else:
                pool.append(conn)
        return res

    async def fetch(self, address: str, oid_b: bytes, offset: int,
                    length: int, dest=None
                    ) -> Optional[Tuple[int, Any]]:
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._fetch_blocking, address, oid_b, offset, length,
            dest)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            pools, self._pools = self._pools, {}
        for pool in pools.values():
            for conn in pool:
                conn.close()


def plan_broadcast_tree(targets: List[Any], fanout: int
                        ) -> List[Tuple[Any, List[Any]]]:
    """Partition an ordered target list into ≤`fanout` (child, subtree)
    slices for the relay tree: the caller sends to each child, each
    child recurses on its subtree. Depth is ceil(log_fanout(N)); every
    node's uplink carries at most fanout×size."""
    fanout = max(1, fanout)
    if not targets:
        return []
    k = min(fanout, len(targets))
    children = targets[:k]
    rest = targets[k:]
    plan: List[Tuple[Any, List[Any]]] = []
    base, extra = divmod(len(rest), k)
    pos = 0
    for i in range(k):
        take = base + (1 if i < extra else 0)
        plan.append((children[i], rest[pos:pos + take]))
        pos += take
    return plan


async def fetch_object_range(
    address: str,
    oid_b: bytes,
    offset: int,
    length: int,
    fetcher: "RawChunkFetcher",
    *,
    chunk_bytes: Optional[int] = None,
    dest: Optional[memoryview] = None,
) -> Optional[Tuple[int, memoryview]]:
    """Pull an arbitrary byte range of a remote object — the range-serve
    reuse path for streaming-shuffle bundles: a reducer fetches only its
    partition's slice of a mapper's sealed bundle instead of the whole
    object. Rides the same raw-frame `get_object_chunk` protocol as
    striped_pull (the daemon serves sealed AND in-flight partials), so
    a reducer can start on a bundle while the mapper is still writing
    later partitions.

    Returns (total_object_size, view-of-range) or None when the holder
    does not have the object. `dest` (when given) must be at least
    `length` bytes; the range lands there and the returned view aliases
    it."""
    if chunk_bytes is None:
        from ray_tpu.core.config import get_config

        chunk_bytes = get_config().object_transfer_chunk_bytes
    own = dest is None
    if own:
        dest = memoryview(bytearray(length))
    total_size: Optional[int] = None
    got = 0
    for off, ln in chunk_ranges(length, chunk_bytes) or [(0, 0)]:
        res = await fetcher.fetch(address, oid_b, offset + off, ln,
                                  dest=dest[off:off + ln] if ln else None)
        if res is None:
            return None
        total_size, data = res
        if data is not None and ln:   # small/pickled reply: copy in
            dest[off:off + len(data)] = data[:ln]
            got += min(len(data), ln)
        else:
            got += ln
        # The daemon clamps reads at the object end; a short serve
        # means the requested range overruns the object.
        if offset + off + ln > total_size:
            raise ValueError(
                f"range [{offset}, {offset + length}) overruns object "
                f"of {total_size} bytes")
    return (total_size or 0), dest[:got]


def make_transfer_metrics(tags: Dict[str, str]) -> Dict[str, Any]:
    """Per-component transfer metric handles. Instances created under
    the same name share sample storage (registry adoption); per-
    daemon/worker accounting lives in the default tags — filter
    samples by node_id to read one component's counts."""
    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    return {
        "bytes_in": Counter(
            "raytpu_transfer_in_bytes_total",
            "Object chunk bytes received over the transfer plane"
        ).set_default_tags(tags),
        "bytes_out": Counter(
            "raytpu_transfer_out_bytes_total",
            "Object chunk bytes served over the transfer plane"
        ).set_default_tags(tags),
        "inflight": Gauge(
            "raytpu_transfer_inflight_bytes",
            "Chunk bytes currently in flight (windowed pulls)"
        ).set_default_tags(tags),
        "gbps": Histogram(
            "raytpu_transfer_gigabytes_per_second",
            "Per-transfer goodput",
            boundaries=(0.05, 0.2, 0.5, 1, 2, 5, 10)
        ).set_default_tags(tags),
    }
