"""TPU accelerator manager: topology detection + fractional-host env setup.

Behavior parity with the reference's TPUAcceleratorManager
(ref: python/ray/_private/accelerators/tpu.py:75):

- pod type from GKE env (``TPU_ACCELERATOR_TYPE``) or the GCE metadata
  server (``accelerator-type`` key), validated as ``v{gen}-{count}``
  (ref: tpu.py:123-141);
- slice name (``TPU_NAME`` / metadata ``instance-id``) and worker index
  (``TPU_WORKER_ID`` / metadata ``agent-worker-number``, ref: tpu.py:242-272);
- per-node extra resources: ``{tpu_name: 1}`` on every host of a slice and
  ``TPU-{pod_type}-head: 1`` on worker 0 only, so gang jobs can target the
  slice atomically (ref: tpu.py:336-397);
- fractional-host chip visibility: exporting ``TPU_VISIBLE_CHIPS`` +
  ``TPU_CHIPS_PER_HOST_BOUNDS`` + ``TPU_HOST_BOUNDS`` for 1- or 2-chip
  requests (ref: tpu.py:157-197); valid per-task chip counts {1, 2, 4}
  (ref: tpu.py:13).

Detection never blocks: env vars are read directly; the metadata server is
only consulted when ``TPU_SKIP_MDS_QUERY`` is unset, with a short socket
timeout (this container is zero-egress, so the query is skipped).
"""
from __future__ import annotations

import logging
import os
import re
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

TPU_RESOURCE_NAME = "TPU"
TPU_VALID_CHIP_COUNTS = (1, 2, 4)
TPU_CHIPS_PER_HOST = 4
# v2/v3/v4 pod types count tensorcores (2/chip); v5e+ count chips.
TPU_VERSIONS_COUNTING_CORES = {"v2", "v3", "v4"}

GKE_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"
GKE_WORKER_ID_ENV = "TPU_WORKER_ID"
GKE_TPU_NAME_ENV = "TPU_NAME"
VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
HOST_BOUNDS_ENV = "TPU_HOST_BOUNDS"
NOSET_VISIBLE_CHIPS_ENV = "RAY_TPU_NOSET_TPU_VISIBLE_CHIPS"

_POD_TYPE_RE = re.compile(r"^v\d+[a-zA-Z]*-\d+$")

_MDS_URL = "http://metadata.google.internal/computeMetadata/v1/instance/attributes/"


_metadata_cache: Dict[str, Optional[str]] = {}


def _metadata(key: str) -> Optional[str]:
    """GCE instance-metadata lookup: opt-in, bounded, cached.

    Off by default — only a GCE VM has the metadata server, and on any
    other network the DNS resolution alone can stall daemon startup (the
    urlopen timeout does not bound it). Enable with
    ``RAY_TPU_MDS_QUERY=1`` on real GCE TPU VMs; GKE deployments use the
    env vars and never need it. ``TPU_SKIP_MDS_QUERY`` force-disables.
    """
    if os.environ.get("TPU_SKIP_MDS_QUERY"):
        return None
    # lint: allow-knob -- hardware-probe gate read before any config exists
    if os.environ.get("RAY_TPU_MDS_QUERY", "").lower() not in ("1", "true"):
        return None
    if key in _metadata_cache:
        return _metadata_cache[key]
    value = None
    try:
        import urllib.request

        req = urllib.request.Request(
            _MDS_URL + key, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=2) as resp:
            if resp.status == 200:
                value = resp.read().decode()
    except Exception as e:  # noqa: BLE001 — metadata absent off-GCE
        logger.debug("TPU metadata query %s failed: %s", key, e)
    _metadata_cache[key] = value
    return value


def is_valid_pod_type(pod_type: str) -> bool:
    return bool(_POD_TYPE_RE.match(pod_type))


def get_pod_type() -> Optional[str]:
    """Slice pod type, e.g. ``v5e-16`` (GKE env, else GCE metadata)."""
    pt = os.environ.get(GKE_ACCELERATOR_TYPE_ENV) or _metadata("accelerator-type")
    if pt and is_valid_pod_type(pt):
        return pt
    return None


def get_tpu_name() -> Optional[str]:
    return os.environ.get(GKE_TPU_NAME_ENV) or _metadata("instance-id")


def get_worker_id() -> Optional[int]:
    raw = os.environ.get(GKE_WORKER_ID_ENV) or _metadata("agent-worker-number")
    try:
        return int(raw) if raw is not None and raw != "" else None
    except ValueError:
        return None


def num_hosts_in_pod(pod_type: Optional[str] = None) -> Optional[int]:
    """Host count of the slice this node belongs to (v4-16 → 2, v5e-16 → 4)."""
    pod_type = pod_type or get_pod_type()
    if not pod_type:
        return None
    version, _, count = pod_type.partition("-")
    n = int(count)
    if version in TPU_VERSIONS_COUNTING_CORES:
        return max(1, n // (TPU_CHIPS_PER_HOST * 2))
    return max(1, n // TPU_CHIPS_PER_HOST)


def accelerator_version(pod_type: Optional[str] = None) -> Optional[str]:
    """``TPU-V5E``-style generation label (ref: tpu.py:289-334)."""
    pod_type = pod_type or get_pod_type()
    if not pod_type:
        return None
    return "TPU-" + pod_type.split("-")[0].upper()


def head_resource_name(pod_type: str) -> str:
    return f"TPU-{pod_type}-head"


def tpu_extra_resources(num_chips: int) -> Dict[str, float]:
    """Slice-gang custom resources for this node (ref: tpu.py:336-397).

    Every host of slice ``my-tpu`` (a v5e-16, say) carries ``{"my-tpu": 1}``;
    worker 0 additionally carries ``{"TPU-v5e-16-head": 1}``. A gang driver
    task targets the head resource, discovers the slice name + host count,
    then fans per-host tasks onto ``{tpu_name: 1, TPU: 4}``.
    """
    res: Dict[str, float] = {}
    pod_type = get_pod_type()
    name = get_tpu_name()
    worker_id = get_worker_id()
    ver = accelerator_version(pod_type)
    if ver:
        res[f"accelerator_type:{ver}"] = 1.0
    if name and pod_type and worker_id is not None:
        res[name] = 1.0
        if worker_id == 0:
            res[head_resource_name(pod_type)] = 1.0
    return res


def validate_chip_request(quantity: float) -> Tuple[bool, Optional[str]]:
    """Per-task/actor TPU chip counts must tile a host (ref: tpu.py:144-155)."""
    if quantity in TPU_VALID_CHIP_COUNTS:
        return True, None
    return False, (
        f"Requested TPU={quantity}, which is not a supported per-host chip "
        f"configuration; supported: {TPU_VALID_CHIP_COUNTS}")


def visible_chip_env(chip_ids: List[int]) -> Dict[str, str]:
    """Env vars that scope a worker process to a subset of the host's chips
    (ref: tpu.py:157-197). Empty dict when all 4 chips are granted (the
    runtime's defaults already see the whole host)."""
    n = len(chip_ids)
    if n >= TPU_CHIPS_PER_HOST:
        return {}
    env = {VISIBLE_CHIPS_ENV: ",".join(str(i) for i in chip_ids)}
    if n == 1:
        env[CHIPS_PER_HOST_BOUNDS_ENV] = "1,1,1"
        env[HOST_BOUNDS_ENV] = "1,1,1"
    elif n == 2:
        env[CHIPS_PER_HOST_BOUNDS_ENV] = "1,2,1"
        env[HOST_BOUNDS_ENV] = "1,1,1"
    return env


def apply_visible_chips(chip_ids: List[int]) -> None:
    if os.environ.get(NOSET_VISIBLE_CHIPS_ENV):
        return
    for k, v in visible_chip_env(chip_ids).items():
        os.environ[k] = v
