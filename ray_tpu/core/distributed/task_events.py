"""Bounded task-event pipeline: worker buffer -> coalesced flush -> GCS.

Analogue of the reference's task-event plane (ref: src/ray/core_worker/
task_event_buffer.h TaskEventBuffer — a bounded worker-side buffer
flushing coalesced task attempts on an interval; src/ray/gcs/gcs_server/
gcs_task_manager.h GcsTaskManager — per-job capped storage with
oldest-attempt eviction, powering `ray list tasks`). Before this module
the repro had the SINK (an unbounded GCS list) but no pipeline: workers
appended flat records to an ad-hoc list, drops were silent, the driver
never reported submission states, and `list_tasks` could not say whether
its answer was complete.

Two halves:

  TaskEventBuffer  (every process that touches a task: driver records
                   SUBMITTED/LEASED, executors record RUNNING/terminal):
                   status transitions coalesce into ONE record per
                   (task_id, attempt) in a bounded ring; a flusher ships
                   them to the GCS on a coalescing interval OFF the hot
                   path. When the GCS is down or the ring overflows,
                   oldest attempts drop with per-kind counters — task
                   execution never blocks on telemetry.

  GcsTaskManager   (GCS side, registered as the `TaskEvents` service):
                   merges records from all reporters by (job, task,
                   attempt), enforces a per-job cap with oldest-attempt
                   eviction, GCs finished jobs after a TTL, and surfaces
                   dropped/evicted counts through the state API so
                   `list_tasks`/`summarize_tasks` report completeness
                   honestly instead of pretending the window is the
                   world.

Profile events (object transfers, user spans) are opt-in
(RAY_TPU_TASK_EVENTS_PROFILE=1) and ride the same bounded pipeline.
Every knob is a `RAY_TPU_TASK_EVENTS_*` env var (config.py).
"""
from __future__ import annotations

import asyncio
import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

from ray_tpu.core.config import get_config

logger = logging.getLogger(__name__)

# Status-transition order; a record's `state` only moves forward (a late
# SUBMITTED arriving after RUNNING must not regress the attempt).
STATES = ("SUBMITTED", "LEASED", "RUNNING", "FINISHED", "FAILED")
_RANK = {s: i for i, s in enumerate(STATES)}
TERMINAL_STATES = ("FINISHED", "FAILED")

_IDENTITY_FIELDS = ("name", "job_id", "actor_id", "node_id", "worker_id",
                    "pid", "submit_node_id", "submit_pid")

# Per-attempt resource attribution (executor-side TaskUsageProbe) and
# hung-task watchdog annotations (daemon-side auto-captured stack
# dumps) ride the same coalesced record: a newer report overwrites.
_RESOURCE_FIELDS = ("cpu_time_s", "rss_delta_bytes", "rss_peak_bytes",
                    "device_mem_bytes", "device_mem_delta_bytes",
                    "hung", "hung_stack", "hung_ts")
_EXTRA_FIELDS = _IDENTITY_FIELDS + _RESOURCE_FIELDS


def _buffer_metrics() -> dict:
    """Process-wide pipeline counters, created once (many buffers can
    coexist in one process — driver + in-proc harness daemons — and all
    share these through registry adoption)."""
    global _METRICS
    if _METRICS is None:
        from ray_tpu.util.metrics import Counter

        _METRICS = {
            "recorded": Counter(
                "raytpu_task_events_recorded_total",
                "Task events recorded into the local buffer",
                tag_keys=("kind",)),
            "dropped": Counter(
                "raytpu_task_events_dropped_total",
                "Task events dropped (ring overflow while the GCS is "
                "unreachable)", tag_keys=("kind",)),
            "flushed": Counter(
                "raytpu_task_events_flushed_total",
                "Task events successfully flushed to the GCS"),
            "flush_failures": Counter(
                "raytpu_task_events_flush_failures_total",
                "Flush RPCs that failed (events re-buffered)"),
        }
    return _METRICS


_METRICS: Optional[dict] = None


class TaskEventBuffer:
    """Per-process bounded task-event ring + coalescing flusher.

    `flush_fn` is an async callable receiving the payload kwargs for one
    `TaskEvents.add_task_events` RPC; the buffer owns retry/drop policy,
    the caller owns transport. Thread-safe: records come from executor
    threads and the driver's submit path; the flusher runs on the
    process's RPC loop.
    """

    def __init__(self, *,
                 flush_fn: Callable[..., Awaitable[Any]],
                 node_id: str = "",
                 worker_id: str = "",
                 pid: int = 0):
        cfg = get_config()
        self.node_id = node_id
        self.worker_id = worker_id
        self.pid = pid
        self._flush_fn = flush_fn
        self.capacity = max(16, cfg.task_events_max_buffer)
        self.flush_period_s = cfg.task_events_flush_ms / 1000.0
        self._lock = threading.Lock()
        # HOT PATH: raw transitions land here with ONE deque.append —
        # GIL-atomic, no lock, no dict merging. The driver's submit
        # thread, the lane loop, and 4 executor threads all record;
        # a shared mutex here ping-ponged the GIL at 0.5ms switch
        # quanta and cost ~20% of many_tasks throughput. Coalescing
        # happens in the flusher (_apply_pending), off the hot path.
        self._pending: deque = deque()
        # (task_id, attempt) -> coalesced attempt record. Insertion
        # order IS drop order: overflow evicts the oldest attempt.
        # Touched only under _lock (flusher + stats).
        self._attempts: "OrderedDict[Tuple[str, int], dict]" = OrderedDict()
        self._profile: deque = deque()
        # Per-kind drops not yet reported to the GCS (shipped with the
        # next successful flush so the sink can account completeness).
        self._dropped_unreported = {"status": 0, "profile": 0}
        self.dropped_total = {"status": 0, "profile": 0}
        self.flushed_total = 0
        self.flush_failures = 0
        self._m = _buffer_metrics()
        self._spans_pending: List[dict] = []
        self._stop = False

    # -- record path (hot; must never block on the GCS) -----------------
    def record_status(self, task_id: str, attempt: int, state: str,
                      ts: Optional[float] = None,
                      error: Optional[str] = None,
                      **fields) -> None:
        if not get_config().task_events_enabled:
            return
        if len(self._pending) >= self.capacity:
            # The flusher can't keep up (GCS down AND a record storm):
            # drop-at-ingress with an accurate counter rather than grow.
            with self._lock:
                self._drop_locked("status")
            return
        self._pending.append(
            (task_id, int(attempt), state,
             ts if ts is not None else time.time(), error,
             fields or None))

    def record_attempt(self, task_id: str, attempt: int,
                       transitions: List[Tuple[str, float]],
                       error: Optional[str] = None,
                       **fields) -> None:
        """Record several transitions of one attempt with a single
        append — the executor's per-task fast path (an attempt's whole
        SUBMITTED/LEASED/RUNNING/terminal history arrives at once when
        the submission half rides the spec)."""
        if not get_config().task_events_enabled:
            return
        if len(self._pending) >= self.capacity:
            with self._lock:
                self._drop_locked("status")
            return
        self._pending.append(
            (task_id, int(attempt), transitions, None, error,
             fields or None))

    def _apply_one_locked(self, task_id: str, attempt: int, state,
                          ts, error, fields) -> None:
        if not isinstance(state, str):
            # record_attempt fast path: `state` is a whole transition
            # list — build (or fold into) the record in one shot, no
            # per-transition dispatch. This is the executor's per-task
            # path; an eager RUNNING mark may already hold the slot.
            transitions = state
            key = (task_id, attempt)
            last_state, last_ts = transitions[-1]
            rec = self._attempts.get(key)
            if rec is None:
                while len(self._attempts) >= self.capacity:
                    self._attempts.popitem(last=False)
                    self._drop_locked("status")
                rec = self._attempts[key] = {
                    "task_id": task_id, "attempt": attempt,
                    "state": last_state,
                    "state_ts": dict(transitions)}
            else:
                st = rec["state_ts"]
                for s2, t2 in transitions:
                    if s2 not in st or s2 in TERMINAL_STATES:
                        st[s2] = t2
                if (_RANK.get(last_state, 0)
                        >= _RANK.get(rec["state"], 0)):
                    rec["state"] = last_state
            run_ts = rec["state_ts"].get("RUNNING")
            if run_ts is not None:
                rec.setdefault("start_ts", run_ts)
            if last_state in TERMINAL_STATES:
                rec["end_ts"] = last_ts
            if error is not None:
                rec["error"] = error
            if fields:
                for k in _EXTRA_FIELDS:
                    v = fields.get(k)
                    if v is not None:
                        rec[k] = v
            return
        key = (task_id, attempt)
        rec = self._attempts.get(key)
        if rec is None:
            while len(self._attempts) >= self.capacity:
                self._attempts.popitem(last=False)
                self._drop_locked("status")
            rec = self._attempts[key] = {
                "task_id": task_id, "attempt": attempt,
                "state": state, "state_ts": {},
            }
        # Identity is per-SIDE: submission states stamp the caller's
        # process (submit_*), execution states the worker's — the
        # GCS merge must not let a driver's flush claim the
        # execution row (the timeline draws its flow arrow between
        # exactly these two identities).
        if _RANK.get(state, 0) < _RANK["RUNNING"]:
            rec.setdefault("submit_node_id", self.node_id or None)
            rec.setdefault("submit_pid", self.pid or None)
        else:
            rec.setdefault("node_id", self.node_id or None)
            rec.setdefault("worker_id", self.worker_id or None)
            rec.setdefault("pid", self.pid or None)
        st = rec["state_ts"]
        # Keep the FIRST timestamp per state (a retried record_status
        # must not slide history), but let terminal states overwrite
        # (a retry's new outcome supersedes).
        if state not in st or state in TERMINAL_STATES:
            st[state] = ts
        if _RANK.get(state, 0) >= _RANK.get(rec["state"], 0):
            rec["state"] = state
        if state == "RUNNING":
            rec.setdefault("start_ts", ts)
        if state in TERMINAL_STATES:
            rec["end_ts"] = ts
        if error is not None:
            rec["error"] = error
        if fields:
            for k in _EXTRA_FIELDS:
                v = fields.get(k)
                if v is not None:
                    rec[k] = v

    def _apply_pending_locked(self) -> None:
        """Coalesce raw transitions into per-attempt records (flusher
        context). popleft races concurrent appends safely: deque ops are
        GIL-atomic, and anything appended mid-drain just waits for the
        next cycle."""
        n = 0
        while True:
            try:
                item = self._pending.popleft()
            except IndexError:
                break
            self._apply_one_locked(*item)
            n += 1
        if n:
            self._m["recorded"].inc(n, tags={"kind": "status"})

    def record_profile(self, name: str, category: str, start_ts: float,
                       end_ts: float, **attrs) -> None:
        """Opt-in profile event (object transfer, user-annotated work)
        riding the same bounded pipeline (ref: profile events in
        core_worker.proto task events)."""
        cfg = get_config()
        if not (cfg.task_events_enabled and cfg.task_events_profile):
            return
        with self._lock:
            while len(self._profile) >= self.capacity:
                self._profile.popleft()
                self._drop_locked("profile")
            self._profile.append({
                "kind": "profile", "name": name, "category": category,
                "start_ts": start_ts, "end_ts": end_ts,
                "node_id": self.node_id or None,
                "pid": self.pid or None, **attrs,
            })
        self._m["recorded"].inc(tags={"kind": "profile"})

    def _drop_locked(self, kind: str) -> None:
        self._dropped_unreported[kind] += 1
        self.dropped_total[kind] += 1
        self._m["dropped"].inc(tags={"kind": kind})

    # -- flush path ------------------------------------------------------
    def drain(self) -> Optional[dict]:
        """Coalesce + take everything pending as one add_task_events
        payload (None when there is nothing to ship)."""
        with self._lock:
            self._apply_pending_locked()
            if (not self._attempts and not self._profile
                    and not any(self._dropped_unreported.values())):
                return None
            # None-valued identity fields are dead wire weight (a driver
            # record ships no worker identity and vice versa): stripping
            # them shrinks the pickle AND the GCS-side merge loop.
            events = [{k: v for k, v in rec.items() if v is not None}
                      for rec in self._attempts.values()]
            self._attempts = OrderedDict()
            profile = list(self._profile)
            self._profile.clear()
            dropped = dict(self._dropped_unreported)
            self._dropped_unreported = {"status": 0, "profile": 0}
        return {"events": events, "profile": profile, "dropped": dropped}

    def _restore(self, payload: dict) -> None:
        """Put a failed flush back at the FRONT of the ring (oldest
        events drop first on overflow), merging with anything recorded
        while the flush was in flight."""
        with self._lock:
            for kind, n in payload.get("dropped", {}).items():
                self._dropped_unreported[kind] += n
            restored: "OrderedDict[Tuple[str, int], dict]" = OrderedDict()
            for rec in payload.get("events", []):
                restored[(rec["task_id"], rec["attempt"])] = rec
            for key, rec in self._attempts.items():
                old = restored.get(key)
                if old is None:
                    restored[key] = rec
                else:
                    merge_attempt(old, rec)
            self._attempts = restored
            while len(self._attempts) > self.capacity:
                self._attempts.popitem(last=False)
                self._drop_locked("status")
            prof = payload.get("profile", [])
            if prof:
                self._profile.extendleft(reversed(prof))
                while len(self._profile) > self.capacity:
                    self._profile.popleft()
                    self._drop_locked("profile")

    async def flush_once(self) -> bool:
        """One flush attempt; True if something shipped. Failures
        re-buffer (bounded) and count — never raise."""
        payload = self.drain()
        if payload is None:
            return False
        try:
            await self._flush_fn(**payload)
        except asyncio.CancelledError:
            self._restore(payload)
            raise
        except Exception as e:  # noqa: BLE001 — GCS down/mid-restart
            self.flush_failures += 1
            self._m["flush_failures"].inc()
            self._restore(payload)
            logger.debug("task-event flush failed: %s", e)
            return False
        n = len(payload["events"]) + len(payload["profile"])
        self.flushed_total += n
        self._m["flushed"].inc(n)
        return True

    async def flush_loop(self) -> None:
        """Coalescing flusher with idle backoff: a parked worker (one of
        hundreds of warm actors) must not tick at full cadence forever —
        activity snaps the delay back (same discipline as the location
        flusher)."""
        delay = self.flush_period_s
        while not self._stop:
            # A backed-off sleep must still wake within one period of
            # new spans appearing: a long-parked warm worker that lands
            # a restarted train gang mints its whole (short) leg under a
            # 16 s delay and would lose every span at teardown.
            slept = 0.0
            while not self._stop:
                await asyncio.sleep(min(self.flush_period_s,
                                        delay - slept))
                slept += self.flush_period_s
                if slept >= delay or self._spans_waiting():
                    break
            self._drain_span_source()
            shipped = await self._ship_spans()
            if await self.flush_once() or shipped:
                delay = self.flush_period_s
            else:
                delay = min(delay * 2, max(self.flush_period_s, 16.0))

    def _spans_waiting(self) -> bool:
        if self._spans_pending:
            return True
        cfg = get_config()
        if not (cfg.tracing_enabled or cfg.serve_trace_enabled
                or cfg.train_obs_enabled):
            return False
        from ray_tpu.util import tracing

        return tracing.has_pending()

    def _drain_span_source(self) -> None:
        cfg = get_config()
        if (cfg.tracing_enabled or cfg.serve_trace_enabled
                or cfg.train_obs_enabled):
            from ray_tpu.util import tracing

            self._spans_pending.extend(tracing.drain())

    async def flush_final(self) -> None:
        """Last-gasp flush at teardown (gang shutdown, process exit):
        drain freshly minted spans and ship everything still buffered so
        a short-lived leg's trace survives the actor dying before the
        next flush tick. Best effort — the GCS may already be gone."""
        self._drain_span_source()
        await self._ship_spans()
        await self.flush_once()

    async def _ship_spans(self) -> bool:
        spans = self._spans_pending
        if not spans:
            return False
        self._spans_pending = []
        try:
            await self._flush_fn(events=[], profile=spans, dropped={})
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 re-buffer, bounded
            self._spans_pending = spans[-self.capacity:]
            return False
        return True

    def stop(self) -> None:
        self._stop = True

    def stats(self) -> dict:
        with self._lock:
            self._apply_pending_locked()
            return {
                "pending": len(self._attempts),
                "pending_profile": len(self._profile),
                "dropped": dict(self.dropped_total),
                "unreported_dropped": dict(self._dropped_unreported),
                "flushed": self.flushed_total,
                "flush_failures": self.flush_failures,
                "capacity": self.capacity,
            }


def merge_attempt(dst: dict, src: dict) -> None:
    """Fold `src`'s transitions into `dst` (same (task_id, attempt)):
    union of state_ts (src wins ties — it is newer), state advances by
    rank, identity fields fill in. Used by both the buffer's re-buffer
    merge and the GCS's cross-reporter merge (driver knows SUBMITTED,
    the executor knows RUNNING)."""
    st = dst.setdefault("state_ts", {})
    for state, ts in (src.get("state_ts") or {}).items():
        if state not in st or state in TERMINAL_STATES:
            st[state] = ts
    if _RANK.get(src.get("state"), -1) >= _RANK.get(dst.get("state"), -1):
        dst["state"] = src.get("state")
    for k in ("start_ts",):
        if dst.get(k) is None and src.get(k) is not None:
            dst[k] = src[k]
    for k in ("end_ts", "error"):
        if src.get(k) is not None:
            dst[k] = src[k]
    for k in _IDENTITY_FIELDS:
        if dst.get(k) is None and src.get(k) is not None:
            dst[k] = src[k]
    # Resource/hung annotations: the newer report wins (a retry's fresh
    # usage supersedes; the watchdog's hung flag survives the executor's
    # later terminal record because that record simply omits it).
    for k in _RESOURCE_FIELDS:
        if src.get(k) is not None:
            dst[k] = src[k]


class GcsTaskManager:
    """GCS-side task-event store (ref: gcs_task_manager.h): per-job
    capped OrderedDicts of coalesced attempts, span/profile rings, and
    honest accounting of everything dropped or evicted on the way in.
    Registered as the `TaskEvents` RPC service (the name the state API,
    CLI and timeline already speak)."""

    GC_SWEEP_MIN_INTERVAL_S = 5.0

    def __init__(self, max_spans: int = 50000):
        # job_id -> OrderedDict[(task_id, attempt) -> record]
        self._jobs: Dict[str, "OrderedDict[Tuple[str, int], dict]"] = {}
        self._spans: deque = deque(maxlen=max_spans)
        self._profile: deque = deque(maxlen=max_spans)
        self._finished_jobs: Dict[str, float] = {}
        self._last_gc = 0.0
        self.counters = {
            "added": 0, "evicted": 0, "gc_jobs": 0, "gc_events": 0,
            "worker_dropped_status": 0, "worker_dropped_profile": 0,
            "spans": 0, "profile": 0,
        }
        self._evicted_by_job: Dict[str, int] = {}
        from ray_tpu.util.metrics import Counter, Gauge

        self._m_added = Counter(
            "raytpu_gcs_task_events_added_total",
            "Task attempt records merged into the GCS task manager")
        self._m_evicted = Counter(
            "raytpu_gcs_task_events_evicted_total",
            "Oldest attempts evicted by the per-job storage cap")
        self._m_stored = Gauge(
            "raytpu_gcs_task_events_stored",
            "Task attempt records currently stored")

    # -- ingest ----------------------------------------------------------
    def add_task_events(self, events: Optional[List[dict]] = None,
                        profile: Optional[List[dict]] = None,
                        dropped: Optional[Dict[str, int]] = None) -> dict:
        cap = max(1, get_config().task_events_max_per_job)
        n_added = n_evicted = 0
        for rec in events or ():
            job = rec.get("job_id") or ""
            table = self._jobs.get(job)
            if table is None:
                table = self._jobs[job] = OrderedDict()
            key = (rec.get("task_id"), rec.get("attempt", 0))
            cur = table.get(key)
            if cur is None:
                while len(table) >= cap:
                    table.popitem(last=False)
                    n_evicted += 1
                    self._evicted_by_job[job] = \
                        self._evicted_by_job.get(job, 0) + 1
                # The decoded record is ours (fresh off the wire): store
                # it without a defensive copy.
                table[key] = rec
            else:
                merge_attempt(cur, rec)
            n_added += 1
        if n_added:
            self.counters["added"] += n_added
            self._m_added.inc(n_added)
        if n_evicted:
            self.counters["evicted"] += n_evicted
            self._m_evicted.inc(n_evicted)
        for rec in profile or ():
            if rec.get("kind") == "span":
                self._spans.append(rec)
                self.counters["spans"] += 1
            else:
                self._profile.append(rec)
                self.counters["profile"] += 1
        for kind, n in (dropped or {}).items():
            self.counters[f"worker_dropped_{kind}"] = \
                self.counters.get(f"worker_dropped_{kind}", 0) + int(n)
        self._maybe_gc()
        return {"ok": True}

    def add_events(self, events: List[dict]) -> dict:
        """Legacy flat-record surface (spans from pre-pipeline flushers,
        tests, external tools): converted into the coalesced model."""
        status: List[dict] = []
        profile: List[dict] = []
        for e in events or ():
            kind = e.get("kind")
            if kind in ("span", "profile"):
                profile.append(e)
                continue
            rec = {k: e.get(k) for k in
                   ("task_id", "name", "job_id", "actor_id", "node_id",
                    "worker_id", "pid", "error", "start_ts", "end_ts")}
            rec["attempt"] = e.get("attempt", 0)
            rec["state"] = e.get("state", "RUNNING")
            st = {}
            if e.get("start_ts") is not None:
                st["RUNNING"] = e["start_ts"]
            if (e.get("end_ts") is not None
                    and rec["state"] in TERMINAL_STATES):
                st[rec["state"]] = e["end_ts"]
            rec["state_ts"] = st
            status.append(rec)
        return self.add_task_events(events=status, profile=profile)

    # -- query -----------------------------------------------------------
    def list_events(self, job_id: Optional[str] = None,
                    limit: int = 10000) -> List[dict]:
        """Flattened rows, newest-last-activity first: task attempts
        (with their full state_ts history), then spans and profile
        events (kind-tagged; the state API filters those out)."""
        rows: List[dict] = []
        for job, table in self._jobs.items():
            if job_id is not None and job != job_id:
                continue
            rows.extend(table.values())
        rows.sort(key=lambda r: r.get("end_ts")
                  or max(r.get("state_ts", {}).values(), default=0.0),
                  reverse=True)
        rows = [dict(r) for r in rows[:limit]]
        room = limit - len(rows)
        if room > 0 and job_id is None:
            extra = list(self._spans) + list(self._profile)
            rows.extend(extra[-room:])
        return rows

    def list_spans(self, trace_id: Optional[str] = None,
                   limit: int = 10000) -> List[dict]:
        """Tracing spans oldest-first, optionally filtered to one trace.
        Serve traces use the request id as trace id, so this is the
        `ray-tpu serve trace <request-id>` backend."""
        rows = [dict(s) for s in self._spans
                if trace_id is None or s.get("trace_id") == trace_id]
        return rows[-limit:]

    def get_task(self, task_id: str) -> List[dict]:
        """Every stored attempt of one task (ref: `ray get tasks`)."""
        out = []
        for table in self._jobs.values():
            for (tid, _attempt), rec in table.items():
                if tid == task_id:
                    out.append(dict(rec))
        out.sort(key=lambda r: r.get("attempt", 0))
        return out

    def stats(self) -> dict:
        """Completeness accounting for the state API: how much telemetry
        exists vs. how much was dropped (worker-side) or evicted
        (GCS-side cap) or GC'd."""
        stored = sum(len(t) for t in self._jobs.values())
        self._m_stored.set(stored)
        return {
            "jobs": len(self._jobs),
            "stored": stored,
            "spans": len(self._spans),
            "profile": len(self._profile),
            "evicted_by_job": dict(self._evicted_by_job),
            **self.counters,
        }

    def summarize(self) -> dict:
        """Per-name state counts + per-name resource rollups (p50/p99
        cpu/rss over the stored window) plus completeness meta (the
        honest version of `ray summary tasks`)."""
        from ray_tpu.util.metrics import percentile

        names: Dict[str, Dict[str, int]] = {}
        res: Dict[str, Dict[str, list]] = {}
        for table in self._jobs.values():
            for rec in table.values():
                name = rec.get("name") or "task"
                per = names.setdefault(name, {})
                state = rec.get("state", "UNKNOWN")
                per[state] = per.get(state, 0) + 1
                cpu = rec.get("cpu_time_s")
                rss = rec.get("rss_delta_bytes")
                if cpu is None and rss is None:
                    continue
                u = res.setdefault(name, {"cpu": [], "rss": []})
                if cpu is not None:
                    u["cpu"].append(cpu)
                if rss is not None:
                    u["rss"].append(rss)
        usage = {}
        for name, u in res.items():
            usage[name] = {
                "n": max(len(u["cpu"]), len(u["rss"])),
                "cpu_time_s": {
                    "p50": percentile(u["cpu"], 50),
                    "p99": percentile(u["cpu"], 99),
                    "max": max(u["cpu"], default=0.0),
                },
                "rss_delta_bytes": {
                    "p50": percentile(u["rss"], 50),
                    "p99": percentile(u["rss"], 99),
                    "max": max(u["rss"], default=0),
                },
            }
        s = self.stats()
        return {"tasks": names,
                "usage": usage,
                "completeness": {
                    "stored": s["stored"],
                    "evicted": s["evicted"],
                    "worker_dropped_status":
                        s.get("worker_dropped_status", 0),
                    "worker_dropped_profile":
                        s.get("worker_dropped_profile", 0),
                    "gc_events": s["gc_events"],
                }}

    def hung_tasks(self, limit: int = 100) -> List[dict]:
        """Attempts the watchdog flagged as hung that are STILL running
        (a flagged attempt that later finished drops out — the flag
        stays on the record for post-mortems, but the live view answers
        "what is stuck right now"). Newest-flagged first."""
        out: List[dict] = []
        for table in self._jobs.values():
            for rec in table.values():
                if not rec.get("hung") or rec.get("state") != "RUNNING":
                    continue
                out.append({k: rec.get(k) for k in (
                    "task_id", "attempt", "name", "job_id", "node_id",
                    "worker_id", "pid", "hung_ts", "start_ts")})
        out.sort(key=lambda r: r.get("hung_ts") or 0.0, reverse=True)
        return out[:limit]

    # -- lifecycle -------------------------------------------------------
    def on_job_finished(self, job_id: str) -> None:
        self._finished_jobs[job_id] = time.time()

    def _maybe_gc(self) -> None:
        now = time.time()
        if now - self._last_gc < self.GC_SWEEP_MIN_INTERVAL_S:
            return
        self._last_gc = now
        self.gc_finished_jobs(now)

    def gc_finished_jobs(self, now: Optional[float] = None) -> int:
        """Drop stored events of jobs finished longer than the TTL ago;
        returns events freed. Called lazily from the ingest path and
        directly by tests."""
        now = now if now is not None else time.time()
        ttl = get_config().task_events_finished_job_ttl_s
        freed = 0
        for job_id, t_finished in list(self._finished_jobs.items()):
            if now - t_finished < ttl:
                continue
            table = self._jobs.pop(job_id, None)
            self._finished_jobs.pop(job_id, None)
            self._evicted_by_job.pop(job_id, None)
            if table:
                freed += len(table)
                self.counters["gc_events"] += len(table)
                self.counters["gc_jobs"] += 1
        return freed
