"""Virtual-daemon harness: thousands of node-daemon stand-ins in one process.

The scale envelope of the control plane (how many nodes can the GCS sync?)
is a different question from the scale envelope of one host (how many
worker processes fit?). The reference answers the first with its
many-nodes release tests against real clusters; on a single VM we answer
it the same way the reference's `fake_cluster` + syncer benchmarks do —
each virtual node runs the REAL registration RPC and the REAL NodeSyncer
protocol (versioned deltas, keepalives, resync), but owns no RpcServer, no
object store, and no worker processes. Many virtual nodes multiplex over a
few shared AsyncRpcClients, so 1000 nodes cost 1000 asyncio tasks + a
handful of sockets, not 1000 processes.

Used by bench_scale.py's `many_nodes` probe and the slow-marked pytest
probe in tests/test_scale_smoke.py.
"""
from __future__ import annotations

import asyncio
import random
from typing import Dict, List, Optional

from ray_tpu.core.distributed.rpc import AsyncRpcClient
from ray_tpu.core.distributed.scheduler import ClusterView
from ray_tpu.core.distributed.syncer import NodeSyncer


class VirtualNode:
    """One in-process node: a real NodeSyncer over synthetic local state."""

    def __init__(self, *, client: AsyncRpcClient, node_id: str,
                 num_cpus: float = 4.0, seed: int = 0,
                 report_interval_s: float = 0.5,
                 keepalive_s: float = 2.0, subscribe: bool = False):
        self.client = client
        self.node_id = node_id
        self.num_cpus = float(num_cpus)
        self.subscribe = subscribe
        self._rng = random.Random(seed)
        self.state: Dict = {
            "available": {"CPU": self.num_cpus},
            "queued": [],
            "store_used": 0, "store_objects": 0, "spilled_bytes": 0,
            "workers": 0, "idle_workers": 0, "busy_workers": 0,
        }
        self.view = ClusterView()       # fan-out lands here if subscribed
        self.syncer = NodeSyncer(
            gcs=client, node_id=node_id,
            collect=lambda: {k: (dict(v) if isinstance(v, dict)
                                 else list(v) if isinstance(v, list) else v)
                             for k, v in self.state.items()},
            on_reregister=self._register,
            report_interval_s=report_interval_s, keepalive_s=keepalive_s)
        self._tasks: List[asyncio.Task] = []

    async def _register(self) -> None:
        await self.client.call(
            "NodeInfo", "register_node", node_id=self.node_id,
            address=f"virtual:{self.node_id[:8]}",
            resources={"CPU": self.num_cpus}, store_dir="",
            labels={"virtual": "1"}, timeout=30)
        self.syncer.force_full_resync()

    async def start(self) -> None:
        await self._register()
        self._tasks = [asyncio.ensure_future(self.syncer.report_loop())]
        if self.subscribe:
            self._tasks.append(
                asyncio.ensure_future(self.syncer.subscribe_loop(self.view)))

    def churn(self) -> None:
        """One synthetic load change: some CPUs become busy/free, the
        worker pool and store wiggle — exactly the fields a real daemon
        reports. The next report tick ships it as one delta."""
        busy = self._rng.randint(0, int(self.num_cpus))
        self.state["available"] = {"CPU": self.num_cpus - busy}
        self.state["busy_workers"] = busy
        self.state["workers"] = busy + self.state["idle_workers"]
        self.state["store_used"] = self._rng.randrange(0, 1 << 24)
        self.syncer.mark_dirty()

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass
        self._tasks = []


class VirtualCluster:
    """N virtual nodes multiplexed over `num_clients` RPC connections."""

    def __init__(self, gcs_address: str, *, n_nodes: int,
                 num_clients: int = 8, num_cpus: float = 4.0,
                 report_interval_s: float = 0.5, keepalive_s: float = 2.0,
                 subscribers: int = 4, seed: int = 0):
        self.gcs_address = gcs_address
        self.clients = [AsyncRpcClient(gcs_address)
                        for _ in range(max(1, num_clients))]
        self.nodes: List[VirtualNode] = []
        rng = random.Random(seed)
        for i in range(n_nodes):
            self.nodes.append(VirtualNode(
                client=self.clients[i % len(self.clients)],
                node_id=f"virt{i:05d}" + "%08x" % rng.getrandbits(32),
                num_cpus=num_cpus, seed=rng.getrandbits(32),
                report_interval_s=report_interval_s,
                keepalive_s=keepalive_s,
                # Only a sample subscribes to the fan-out: every real
                # daemon would, but N subscribers x N nodes of broadcast
                # is O(N^2) loopback bytes that measure the bench host,
                # not the sync path.
                subscribe=i < subscribers))

    async def start(self, registration_concurrency: int = 64) -> None:
        sem = asyncio.Semaphore(registration_concurrency)

        async def boot(node: VirtualNode) -> None:
            async with sem:
                await node.start()

        await asyncio.gather(*(boot(n) for n in self.nodes))

    def churn(self, fraction: float = 0.2,
              rng: Optional[random.Random] = None) -> int:
        rng = rng or random
        k = max(1, int(len(self.nodes) * fraction))
        for node in rng.sample(self.nodes, k):
            node.churn()
        return k

    def aggregate_stats(self) -> Dict[str, float]:
        agg: Dict[str, float] = {}
        for node in self.nodes:
            for k, v in node.syncer.stats.items():
                agg[k] = agg.get(k, 0) + v
        agg["nodes"] = len(self.nodes)
        return agg

    async def stop(self) -> None:
        await asyncio.gather(*(n.stop() for n in self.nodes))
        for c in self.clients:
            await c.close()


class InProcDaemonCluster:
    """N REAL NodeDaemons + one GcsServer on one event loop — the
    object-plane sibling of VirtualCluster: real RPC servers, real shm
    object stores, the real transfer plane (raw frames, create-then-
    fill, striped pulls, broadcast relays), but no worker processes
    (zygote/prestart forced off for the process). Used by the
    object_transfer / broadcast probes in bench_scale.py and the
    transfer tests.
    """

    def __init__(self, n_nodes: int, *, store_capacity: int = 512 << 20,
                 num_cpus: float = 1.0):
        self.n_nodes = n_nodes
        self.store_capacity = store_capacity
        self.num_cpus = num_cpus
        self.gcs = None
        self.daemons: List = []

    async def start(self) -> None:
        import uuid

        from ray_tpu.core.config import get_config
        from ray_tpu.core.distributed.gcs_server import GcsServer
        from ray_tpu.core.distributed.node_daemon import NodeDaemon

        cfg = get_config()
        # Daemons in THIS process must not fork zygotes or prestart
        # worker processes — the harness exercises the object plane.
        # Saved + restored on stop(): the config singleton is process-
        # wide and later tests may exercise the zygote path.
        self._saved_cfg = (cfg.zygote_enabled, cfg.worker_prestart_enabled)
        cfg.zygote_enabled = False
        cfg.worker_prestart_enabled = False
        self.gcs = GcsServer()
        port = await self.gcs.start()
        for i in range(self.n_nodes):
            daemon = NodeDaemon(
                gcs_address=f"127.0.0.1:{port}",
                node_id=f"inproc{i:03d}" + uuid.uuid4().hex[:10],
                num_cpus=self.num_cpus,
                store_dir=f"/dev/shm/raytpu_inproc_{uuid.uuid4().hex[:12]}",
                object_store_memory=self.store_capacity)
            await daemon.start()
            self.daemons.append(daemon)

    @property
    def addresses(self) -> List[str]:
        return [d.server.address for d in self.daemons]

    async def stop(self) -> None:
        for d in self.daemons:
            try:
                await d.stop()
            except Exception:  # noqa: BLE001
                pass
        self.daemons = []
        if self.gcs is not None:
            await self.gcs.stop()
            self.gcs = None
        saved = getattr(self, "_saved_cfg", None)
        if saved is not None:
            from ray_tpu.core.config import get_config

            cfg = get_config()
            cfg.zygote_enabled, cfg.worker_prestart_enabled = saved
