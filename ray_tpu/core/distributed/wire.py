"""Typed wire codec + protocol versioning for the control plane.

The reference isolates every process boundary behind proto3 schemas
(ref: src/ray/protobuf/core_worker.proto:425 and 23 sibling files), so
version skew or a non-Python peer fails with a typed error instead of a
deserialize crash. Our equivalent, sized to the actual cross-language
surface (KV, task submit, worker handshake, actor calls):

* a PROTOCOL VERSION byte rides in every RPC frame header (rpc.py);
  a mismatched peer gets a clear "protocol version mismatch" error,
  never a garbled unpickle;
* every payload is prefixed with a CODEC byte: pickle (0) remains the
  Python<->Python codec — arbitrary objects, exceptions with state —
  while the TYPED codec (1) is a self-describing binary schema over
  the cross-language data model (None/bool/int64/float64/bytes/str/
  list/dict), hand-decodable from C++ in ~100 lines with no pickle
  opcode machine. The C++ headers (cpp/include/ray_tpu_client/,
  ray_tpu_worker/) implement exactly this codec.

Typed format, little-endian throughout (x86/arm64):

    value := 0x00                      # None
           | 0x01 | 0x02               # True / False
           | 0x03 i64                  # int
           | 0x04 f64                  # float
           | 0x05 u32 raw              # bytes
           | 0x06 u32 utf8             # str
           | 0x07 u32 value*           # list (tuples encode as list)
           | 0x08 u32 (value value)*   # dict
           | 0x09                      # out-of-band raw body (RAW codec)

RAW codec (2): the bulk-data frame format. A message whose structure
contains exactly one `Raw(buffer)` marker is encoded as

    payload := 0x02 | u32 hlen | typed(header) | body

where the header is the typed encoding of the message with the marker
replaced by tag 0x09, and the body bytes follow verbatim — no pickle,
no length-prefix copies. The encoder returns (header, body) as SEPARATE
buffers so the transport can writev them (header built once, the body
handed to the socket as the caller's memoryview); the decoder splices a
zero-copy memoryview of the body back into the 0x09 position. This is
the seam object-chunk transfers ride: a 5 MiB chunk crosses the RPC
layer without ever being copied into a pickle stream on either side
(ref: the reference moves chunk payloads as raw grpc bytes fields,
object_manager.proto Push).
"""
from __future__ import annotations

import struct
from typing import Any, Optional, Tuple

# Deliberately outside 1..6: the previous (unversioned) frame format
# carried the frame-TYPE byte at this offset, so any version equal to a
# frame type (REQ=1..CANCEL=6) would make an old-generation peer pass
# the version check and be misparsed instead of cleanly rejected.
# v17: RAW codec (out-of-band binary attachment frames).
PROTOCOL_VERSION = 17

CODEC_PICKLE = 0
CODEC_TYPED = 1
CODEC_RAW = 2

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")

_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_BYTES = 0x05
_T_STR = 0x06
_T_LIST = 0x07
_T_DICT = 0x08
_T_RAW = 0x09


class WireError(ValueError):
    """A value outside the typed model, or a corrupt typed payload."""


class Raw:
    """Marks one buffer in an RPC message for out-of-band raw-frame
    transport. The wrapped buffer never enters a codec stream: the send
    path writes it to the socket directly (after the typed header) and
    the receive path splices a zero-copy memoryview back in its place.

    Deliberately unpicklable: a Raw that escapes the raw-frame scan
    (nested deeper than the bounded scan looks) must fail loudly at
    encode time, not arrive at the peer as an opaque object.
    """

    __slots__ = ("buffer",)

    def __init__(self, buffer):
        self.buffer = buffer

    def __len__(self) -> int:
        return len(self.buffer)

    def __reduce__(self):
        raise WireError(
            "Raw buffer outside a raw-frame position (nest it at the "
            "top levels of the RPC message, see wire.scan_raw)")


def _enc(obj: Any, out: bytearray,
         raw_cell: Optional[list] = None) -> None:
    if obj is None:
        out.append(_T_NONE)
    elif obj is True:
        out.append(_T_TRUE)
    elif obj is False:
        out.append(_T_FALSE)
    elif isinstance(obj, int):
        out.append(_T_INT)
        try:
            out += _I64.pack(obj)
        except struct.error:
            raise WireError(f"int {obj} exceeds int64") from None
    elif isinstance(obj, float):
        out.append(_T_FLOAT)
        out += _F64.pack(obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(_T_BYTES)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(_T_STR)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(obj, (list, tuple)):
        out.append(_T_LIST)
        out += _U32.pack(len(obj))
        for item in obj:
            _enc(item, out, raw_cell)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        out += _U32.pack(len(obj))
        for k, v in obj.items():
            _enc(k, out, raw_cell)
            _enc(v, out, raw_cell)
    elif isinstance(obj, Raw):
        if raw_cell is None:
            raise WireError("Raw buffer is only valid under the RAW codec")
        if raw_cell:
            raise WireError("at most one Raw buffer per RPC message")
        raw_cell.append(obj.buffer)
        out.append(_T_RAW)
    else:
        raise WireError(
            f"{type(obj).__name__} is outside the typed wire model "
            f"(None/bool/int/float/bytes/str/list/dict)")


def typed_dumps(obj: Any) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def raw_dumps(obj: Any) -> Tuple[bytes, Any]:
    """Encode a message containing exactly one Raw marker. Returns
    (header_bytes, body_buffer): the header is `u32 hlen | typed` with
    tag 0x09 at the marker position; the body is the caller's buffer,
    untouched, to be writev'd after the header."""
    out = bytearray()
    cell: list = []
    _enc(obj, out, cell)
    if not cell:
        raise WireError("raw_dumps: message contains no Raw buffer")
    return _U32.pack(len(out)) + bytes(out), cell[0]


def scan_raw(obj: Any, depth: int = 3) -> Optional[Raw]:
    """Bounded search for a Raw marker at the top levels of an RPC
    message (kwargs dicts, reply dicts, small lists). Bounded so the
    control-plane hot path never pays a deep traversal; Raw markers
    nested past the bound fail loudly via Raw.__reduce__."""
    if isinstance(obj, Raw):
        return obj
    if depth <= 0:
        return None
    if isinstance(obj, dict):
        for v in obj.values():
            r = scan_raw(v, depth - 1)
            if r is not None:
                return r
    elif isinstance(obj, (list, tuple)):
        for v in obj[:32]:
            r = scan_raw(v, depth - 1)
            if r is not None:
                return r
    return None


def _dec(data: memoryview, pos: int,
         raw_body: Optional[memoryview] = None) -> Tuple[Any, int]:
    try:
        tag = data[pos]
    except IndexError:
        raise WireError("truncated typed payload") from None
    pos += 1
    try:
        if tag == _T_NONE:
            return None, pos
        if tag == _T_TRUE:
            return True, pos
        if tag == _T_FALSE:
            return False, pos
        if tag == _T_INT:
            return _I64.unpack_from(data, pos)[0], pos + 8
        if tag == _T_FLOAT:
            return _F64.unpack_from(data, pos)[0], pos + 8
        if tag in (_T_BYTES, _T_STR):
            (n,) = _U32.unpack_from(data, pos)
            pos += 4
            if pos + n > len(data):
                raise WireError("truncated typed payload")
            raw = bytes(data[pos:pos + n])
            return (raw if tag == _T_BYTES
                    else raw.decode("utf-8")), pos + n
        if tag == _T_LIST:
            (n,) = _U32.unpack_from(data, pos)
            pos += 4
            items = []
            for _ in range(n):
                item, pos = _dec(data, pos, raw_body)
                items.append(item)
            return items, pos
        if tag == _T_DICT:
            (n,) = _U32.unpack_from(data, pos)
            pos += 4
            d = {}
            for _ in range(n):
                k, pos = _dec(data, pos, raw_body)
                v, pos = _dec(data, pos, raw_body)
                d[k] = v
            return d, pos
        if tag == _T_RAW:
            if raw_body is None:
                raise WireError("0x09 raw tag outside a RAW-codec frame")
            return raw_body, pos
    except struct.error:
        raise WireError("truncated typed payload") from None
    raise WireError(f"unknown typed tag 0x{tag:02x}")


def typed_loads(data) -> Any:
    """Accepts bytes or memoryview (zero-copy slicing off codec bytes)."""
    view = memoryview(data)
    obj, pos = _dec(view, 0)
    if pos != len(view):
        raise WireError(
            f"{len(view) - pos} trailing bytes after typed value")
    return obj


# Placeholder the 0x09 tag decodes to when the body is NOT in hand —
# raw_header_loads callers (recv_into receivers) read the header first,
# then stream the body straight into its destination buffer.
RAW_BODY = type("RawBodyPlaceholder", (), {
    "__repr__": lambda self: "<raw body>"})()


def raw_header_loads(header) -> Any:
    """Decode just the typed header of a RAW frame (no hlen prefix, no
    body): the 0x09 position decodes to the RAW_BODY sentinel. Used by
    direct-to-shm receivers that want the metadata BEFORE reading the
    body, so the body bytes can be received straight into the store
    mmap instead of through an intermediate buffer."""
    view = memoryview(header)
    obj, pos = _dec(view, 0, raw_body=RAW_BODY)
    if pos != len(view):
        raise WireError(
            f"{len(view) - pos} trailing bytes after raw header")
    return obj


def raw_loads(data) -> Any:
    """Decode a RAW-codec payload (after the codec byte): `u32 hlen |
    typed(header) | body`. The body is spliced into the 0x09 position
    as a zero-copy memoryview of `data` — the caller's frame bytes stay
    alive as long as the decoded message references them."""
    view = memoryview(data)
    if len(view) < 4:
        raise WireError("truncated raw frame")
    (hlen,) = _U32.unpack_from(view, 0)
    if 4 + hlen > len(view):
        raise WireError("truncated raw frame header")
    header = view[4:4 + hlen]
    body = view[4 + hlen:]
    obj, pos = _dec(header, 0, raw_body=body)
    if pos != hlen:
        raise WireError(
            f"{hlen - pos} trailing bytes after raw frame header")
    return obj


def typed_safe(obj: Any) -> Any:
    """Project an RPC reply onto the typed model: exceptions become
    'Type: message' strings (a non-Python peer cannot rehydrate them
    anyway — the same rule the reference's cross-language boundary
    applies), other foreign objects become their repr."""
    if obj is None or isinstance(obj, (bool, int, float, bytes, str)):
        return obj
    if isinstance(obj, (list, tuple)):
        return [typed_safe(x) for x in obj]
    if isinstance(obj, dict):
        return {typed_safe(k): typed_safe(v) for k, v in obj.items()}
    if isinstance(obj, BaseException):
        return f"{type(obj).__name__}: {obj}"
    return repr(obj)
