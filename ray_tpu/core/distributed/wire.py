"""Typed wire codec + protocol versioning for the control plane.

The reference isolates every process boundary behind proto3 schemas
(ref: src/ray/protobuf/core_worker.proto:425 and 23 sibling files), so
version skew or a non-Python peer fails with a typed error instead of a
deserialize crash. Our equivalent, sized to the actual cross-language
surface (KV, task submit, worker handshake, actor calls):

* a PROTOCOL VERSION byte rides in every RPC frame header (rpc.py);
  a mismatched peer gets a clear "protocol version mismatch" error,
  never a garbled unpickle;
* every payload is prefixed with a CODEC byte: pickle (0) remains the
  Python<->Python codec — arbitrary objects, exceptions with state —
  while the TYPED codec (1) is a self-describing binary schema over
  the cross-language data model (None/bool/int64/float64/bytes/str/
  list/dict), hand-decodable from C++ in ~100 lines with no pickle
  opcode machine. The C++ headers (cpp/include/ray_tpu_client/,
  ray_tpu_worker/) implement exactly this codec.

Typed format, little-endian throughout (x86/arm64):

    value := 0x00                      # None
           | 0x01 | 0x02               # True / False
           | 0x03 i64                  # int
           | 0x04 f64                  # float
           | 0x05 u32 raw              # bytes
           | 0x06 u32 utf8             # str
           | 0x07 u32 value*           # list (tuples encode as list)
           | 0x08 u32 (value value)*   # dict
"""
from __future__ import annotations

import struct
from typing import Any, Tuple

# Deliberately outside 1..6: the previous (unversioned) frame format
# carried the frame-TYPE byte at this offset, so any version equal to a
# frame type (REQ=1..CANCEL=6) would make an old-generation peer pass
# the version check and be misparsed instead of cleanly rejected.
PROTOCOL_VERSION = 16

CODEC_PICKLE = 0
CODEC_TYPED = 1

_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")

_T_NONE = 0x00
_T_TRUE = 0x01
_T_FALSE = 0x02
_T_INT = 0x03
_T_FLOAT = 0x04
_T_BYTES = 0x05
_T_STR = 0x06
_T_LIST = 0x07
_T_DICT = 0x08


class WireError(ValueError):
    """A value outside the typed model, or a corrupt typed payload."""


def _enc(obj: Any, out: bytearray) -> None:
    if obj is None:
        out.append(_T_NONE)
    elif obj is True:
        out.append(_T_TRUE)
    elif obj is False:
        out.append(_T_FALSE)
    elif isinstance(obj, int):
        out.append(_T_INT)
        try:
            out += _I64.pack(obj)
        except struct.error:
            raise WireError(f"int {obj} exceeds int64") from None
    elif isinstance(obj, float):
        out.append(_T_FLOAT)
        out += _F64.pack(obj)
    elif isinstance(obj, (bytes, bytearray, memoryview)):
        b = bytes(obj)
        out.append(_T_BYTES)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(_T_STR)
        out += _U32.pack(len(b))
        out += b
    elif isinstance(obj, (list, tuple)):
        out.append(_T_LIST)
        out += _U32.pack(len(obj))
        for item in obj:
            _enc(item, out)
    elif isinstance(obj, dict):
        out.append(_T_DICT)
        out += _U32.pack(len(obj))
        for k, v in obj.items():
            _enc(k, out)
            _enc(v, out)
    else:
        raise WireError(
            f"{type(obj).__name__} is outside the typed wire model "
            f"(None/bool/int/float/bytes/str/list/dict)")


def typed_dumps(obj: Any) -> bytes:
    out = bytearray()
    _enc(obj, out)
    return bytes(out)


def _dec(data: memoryview, pos: int) -> Tuple[Any, int]:
    try:
        tag = data[pos]
    except IndexError:
        raise WireError("truncated typed payload") from None
    pos += 1
    try:
        if tag == _T_NONE:
            return None, pos
        if tag == _T_TRUE:
            return True, pos
        if tag == _T_FALSE:
            return False, pos
        if tag == _T_INT:
            return _I64.unpack_from(data, pos)[0], pos + 8
        if tag == _T_FLOAT:
            return _F64.unpack_from(data, pos)[0], pos + 8
        if tag in (_T_BYTES, _T_STR):
            (n,) = _U32.unpack_from(data, pos)
            pos += 4
            if pos + n > len(data):
                raise WireError("truncated typed payload")
            raw = bytes(data[pos:pos + n])
            return (raw if tag == _T_BYTES
                    else raw.decode("utf-8")), pos + n
        if tag == _T_LIST:
            (n,) = _U32.unpack_from(data, pos)
            pos += 4
            items = []
            for _ in range(n):
                item, pos = _dec(data, pos)
                items.append(item)
            return items, pos
        if tag == _T_DICT:
            (n,) = _U32.unpack_from(data, pos)
            pos += 4
            d = {}
            for _ in range(n):
                k, pos = _dec(data, pos)
                v, pos = _dec(data, pos)
                d[k] = v
            return d, pos
    except struct.error:
        raise WireError("truncated typed payload") from None
    raise WireError(f"unknown typed tag 0x{tag:02x}")


def typed_loads(data) -> Any:
    """Accepts bytes or memoryview (zero-copy slicing off codec bytes)."""
    view = memoryview(data)
    obj, pos = _dec(view, 0)
    if pos != len(view):
        raise WireError(
            f"{len(view) - pos} trailing bytes after typed value")
    return obj


def typed_safe(obj: Any) -> Any:
    """Project an RPC reply onto the typed model: exceptions become
    'Type: message' strings (a non-Python peer cannot rehydrate them
    anyway — the same rule the reference's cross-language boundary
    applies), other foreign objects become their repr."""
    if obj is None or isinstance(obj, (bool, int, float, bytes, str)):
        return obj
    if isinstance(obj, (list, tuple)):
        return [typed_safe(x) for x in obj]
    if isinstance(obj, dict):
        return {typed_safe(k): typed_safe(v) for k, v in obj.items()}
    if isinstance(obj, BaseException):
        return f"{type(obj).__name__}: {obj}"
    return repr(obj)
