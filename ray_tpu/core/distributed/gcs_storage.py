"""Durable GCS state: snapshot + write-ahead log on local disk.

Analogue of the reference's pluggable GCS storage
(ref: src/ray/gcs/store_client/ — InMemoryStoreClient vs
RedisStoreClient, selected by the `gcs_storage` knob,
ray_config_def.h:402; with Redis the GCS survives restarts and raylets
reconnect within gcs_rpc_server_reconnect_timeout_s :439). This build's
durable backend is a file pair per storage dir:

    snapshot.pkl   full {table: {key: value}} image
    wal.pkl        length-prefixed pickled (op, table, key, value)
                   records appended after the snapshot

Writes append to the WAL synchronously (one small write + flush +
fsync — flush alone only reaches the OS page cache, which a host/power
failure loses; RAY_TPU_GCS_FSYNC=0 downgrades to process-restart-only
durability when write latency matters more). A snapshot rewrite folds
the WAL in whenever it grows past `snapshot_every` records. Load =
snapshot + WAL replay.
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Any, Dict, Optional

_LEN = struct.Struct("<I")


class PersistentStore:
    def __init__(self, directory: str, snapshot_every: int = 5000):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._snapshot_path = os.path.join(directory, "snapshot.pkl")
        self._wal_path = os.path.join(directory, "wal.pkl")
        self._snapshot_every = snapshot_every
        from ray_tpu.core.config import get_config

        self._fsync = bool(get_config().gcs_fsync)
        self._lock = threading.Lock()
        self._tables: Dict[str, Dict[Any, Any]] = {}
        self._wal_count = 0
        good_bytes = self._load()
        # Truncate any torn/corrupt tail BEFORE appending: records
        # written after unreadable bytes would be unreachable on the
        # next replay (silent data loss on the second restart).
        if os.path.exists(self._wal_path) and \
                os.path.getsize(self._wal_path) > good_bytes:
            with open(self._wal_path, "r+b") as f:
                f.truncate(good_bytes)
        self._wal = open(self._wal_path, "ab")

    # -- recovery -------------------------------------------------------
    def _load(self) -> int:
        """Replay snapshot + WAL; returns the byte offset of the last
        fully-valid WAL record (the truncation point for torn tails)."""
        if os.path.exists(self._snapshot_path):
            with open(self._snapshot_path, "rb") as f:
                self._tables = pickle.load(f)
        good_bytes = 0
        if os.path.exists(self._wal_path):
            with open(self._wal_path, "rb") as f:
                while True:
                    head = f.read(_LEN.size)
                    if len(head) < _LEN.size:
                        break
                    (n,) = _LEN.unpack(head)
                    blob = f.read(n)
                    if len(blob) < n:
                        break  # torn tail write
                    try:
                        op, table, key, value = pickle.loads(blob)
                    except Exception:  # noqa: BLE001 corrupt tail
                        break
                    if op == "put":
                        self._tables.setdefault(table, {})[key] = value
                    else:
                        self._tables.get(table, {}).pop(key, None)
                    self._wal_count += 1
                    good_bytes = f.tell()
        return good_bytes

    # -- write path -----------------------------------------------------
    def _append(self, op: str, table: str, key: Any, value: Any) -> None:
        blob = pickle.dumps((op, table, key, value), protocol=5)
        with self._lock:
            self._wal.write(_LEN.pack(len(blob)) + blob)
            self._wal.flush()
            if self._fsync:
                os.fsync(self._wal.fileno())
            self._wal_count += 1
            if self._wal_count >= self._snapshot_every:
                self._compact_locked()

    def _compact_locked(self) -> None:
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._tables, f, protocol=5)
            if self._fsync:
                f.flush()
                os.fsync(f.fileno())
        os.replace(tmp, self._snapshot_path)
        self._wal.close()
        self._wal = open(self._wal_path, "wb")
        self._wal_count = 0

    def put(self, table: str, key: Any, value: Any) -> None:
        self._tables.setdefault(table, {})[key] = value
        self._append("put", table, key, value)

    def delete(self, table: str, key: Any) -> None:
        if self._tables.get(table, {}).pop(key, None) is not None:
            self._append("del", table, key, None)

    def all(self, table: str) -> Dict[Any, Any]:
        return dict(self._tables.get(table, {}))

    def close(self) -> None:
        with self._lock:
            try:
                self._wal.close()
            except Exception:  # noqa: BLE001
                pass


class NullStore:
    """In-memory default (the reference's gcs_storage="memory")."""

    def put(self, table: str, key: Any, value: Any) -> None:
        pass

    def delete(self, table: str, key: Any) -> None:
        pass

    def all(self, table: str) -> Dict[Any, Any]:
        return {}

    def close(self) -> None:
        pass


def open_store(directory: Optional[str]):
    return PersistentStore(directory) if directory else NullStore()
