"""Cluster scheduling policies.

Analogue of the reference's scheduler policy umbrella
(ref: src/ray/raylet/scheduling/scheduling_policy.h:26; hybrid top-k design
comment policy/hybrid_scheduling_policy.h:26-49; spread/affinity/bundle
policies in policy/*.h). Operates on a ClusterView assembled from GCS
heartbeats; used both by node daemons (task spillback) and by the GCS
(actor/PG placement).
"""
from __future__ import annotations

import dataclasses
import random
import time
from typing import Dict, List, Optional

from ray_tpu.core.distributed import resources as rs


@dataclasses.dataclass
class NodeView:
    node_id: str
    address: str            # daemon RPC address
    total: rs.ResourceSet
    available: rs.ResourceSet
    alive: bool = True
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    store_dir: str = ""
    last_heartbeat: float = dataclasses.field(default_factory=time.monotonic)
    # Autoscaler inputs (ref: the raylet reports resource load + idle time
    # through the syncer to the GCS autoscaler state,
    # gcs_autoscaler_state_manager.h): demands queued on this node's
    # daemon and the last moment it was observed busy.
    queued: List[rs.ResourceSet] = dataclasses.field(default_factory=list)
    last_busy: float = dataclasses.field(default_factory=time.monotonic)
    # Synced node stats (syncer.py STATE_KEYS): object-store pressure and
    # worker-pool depth, shipped as deltas alongside resources.
    store_used: int = 0
    store_objects: int = 0
    spilled_bytes: int = 0
    workers: int = 0
    idle_workers: int = 0
    busy_workers: int = 0
    # Serve replica gauges aggregated per app on this node (queue depth,
    # active streams, KV-pool occupancy) — the controller's autoscale
    # signal rides the syncer instead of per-decision replica polls.
    serve: Dict[str, dict] = dataclasses.field(default_factory=dict)
    # Train-rank step/phase gauges on this node, keyed run -> "rank@N"
    # (node_daemon._train_state): the GCS TrainRunState's goodput/skew
    # input rides the syncer the same way serve gauges do.
    train: Dict[str, dict] = dataclasses.field(default_factory=dict)


# Dynamic NodeView attributes the syncer may overwrite from a reported
# state dict (the "available"/"queued" pair keeps heartbeat parity).
_SYNCED_ATTRS = ("available", "queued", "store_used", "store_objects",
                 "spilled_bytes", "workers", "idle_workers", "busy_workers",
                 "serve", "train")
# Everything a daemon needs of a peer to make spillback decisions —
# the cluster-view fan-out entry.
_WIRE_ATTRS = ("node_id", "address", "total", "available", "alive",
               "labels", "store_dir", "queued") + _SYNCED_ATTRS[2:]


def node_wire(n: NodeView) -> dict:
    """NodeView -> broadcast wire dict (plain primitives only)."""
    return {a: getattr(n, a) for a in _WIRE_ATTRS}


def apply_node_wire(view: "ClusterView", payload: dict) -> None:
    """Fold a syncer broadcast payload (full or delta) into a view."""
    if payload.get("full"):
        view.nodes = {}
    for nid, wire in (payload.get("nodes") or {}).items():
        n = view.nodes.get(nid)
        if n is None:
            view.nodes[nid] = NodeView(
                node_id=nid, address=wire.get("address", ""),
                total=dict(wire.get("total") or {}),
                available=dict(wire.get("available") or {}),
                alive=wire.get("alive", True),
                labels=dict(wire.get("labels") or {}),
                store_dir=wire.get("store_dir", ""))
            n = view.nodes[nid]
        for attr in _WIRE_ATTRS:
            if attr in wire:
                setattr(n, attr, wire[attr])
        n.last_heartbeat = time.monotonic()
    for nid in payload.get("dead") or ():
        n = view.nodes.get(nid)
        if n is not None:
            n.alive = False


class ClusterView:
    def __init__(self):
        self.nodes: Dict[str, NodeView] = {}

    def alive_nodes(self) -> List[NodeView]:
        return [n for n in self.nodes.values() if n.alive]

    def update(self, node_id: str, available: rs.ResourceSet,
               queued: Optional[List[rs.ResourceSet]] = None) -> None:
        n = self.nodes.get(node_id)
        if n is not None:
            n.available = available
            if queued is not None:
                n.queued = queued
            n.last_heartbeat = time.monotonic()
            if n.queued or rs.utilization(n.total, n.available) > rs.EPS:
                n.last_busy = n.last_heartbeat

    def apply_state(self, node_id: str, state: Dict) -> bool:
        """Apply a (partial) synced state dict — the syncer's delta-apply
        seam. Refreshes liveness exactly like a heartbeat would."""
        n = self.nodes.get(node_id)
        if n is None:
            return False
        for attr in _SYNCED_ATTRS:
            if attr in state:
                setattr(n, attr, state[attr])
        n.last_heartbeat = time.monotonic()
        if n.queued or rs.utilization(n.total, n.available) > rs.EPS:
            n.last_busy = n.last_heartbeat
        return True


def pick_node(
    view: ClusterView,
    demand: rs.ResourceSet,
    *,
    strategy: str = "hybrid",          # hybrid | spread | node_affinity
    local_node_id: Optional[str] = None,
    affinity_node_id: Optional[str] = None,
    affinity_soft: bool = False,
    spread_threshold: float = 0.5,
    top_k_fraction: float = 0.2,
    rng: Optional[random.Random] = None,
) -> Optional[NodeView]:
    """Pick a node for `demand`, or None if nothing fits right now.

    hybrid (default, ref hybrid_scheduling_policy.h): prefer the local node
    while its critical utilization stays under `spread_threshold`; otherwise
    pick uniformly among the top-k least-utilized nodes that fit. This
    approximates bin-packing at low load and spreads at high load.
    """
    rng = rng or random
    alive = view.alive_nodes()
    if not alive:
        return None

    if strategy == "node_affinity" and affinity_node_id is not None:
        n = view.nodes.get(affinity_node_id)
        if n is not None and n.alive and rs.fits(n.available, demand):
            return n
        if not affinity_soft:
            return None
        strategy = "hybrid"

    fitting = [n for n in alive if rs.fits(n.available, demand)]
    if not fitting:
        return None

    def rank(n: NodeView):
        # Primary: least utilized. Tie-breaks come from the synced view:
        # shorter queued backlog, then a warm (idle) worker already
        # booted — landing there turns the spawn into a pool pop.
        return (rs.utilization(n.total, n.available, demand),
                len(n.queued), -n.idle_workers)

    if strategy == "spread":
        # Least utilized first => round-robin-ish spread under churn.
        fitting.sort(key=rank)
        return fitting[0]

    # hybrid
    if local_node_id is not None:
        local = view.nodes.get(local_node_id)
        if (local is not None and local.alive
                and rs.fits(local.available, demand)
                and rs.utilization(local.total, local.available,
                                   demand) < spread_threshold):
            return local
    fitting.sort(key=rank)
    k = max(1, int(len(fitting) * top_k_fraction))
    return rng.choice(fitting[:k])


def pick_feasible_node(view: ClusterView, demand: rs.ResourceSet,
                       exclude: Optional[str] = None) -> Optional[NodeView]:
    """A node whose TOTAL resources could ever satisfy `demand`, preferring
    one that fits right now. Used to forward never-runnable-here requests to
    a node where they can queue (ref: the reference parks infeasible tasks
    in the owning raylet's queue, cluster_task_manager.h:42)."""
    candidates = [n for n in view.alive_nodes()
                  if n.node_id != exclude and rs.feasible(n.total, demand)]
    if not candidates:
        return None
    now = [n for n in candidates if rs.fits(n.available, demand)]
    pool = now or candidates
    pool.sort(key=lambda n: rs.utilization(n.total, n.available, demand))
    return pool[0]


# ---------------------------------------------------------------------------
# Placement group bundle placement (ref: policy/bundle_scheduling_policy.h)
# ---------------------------------------------------------------------------

def place_bundles(
    view: ClusterView,
    bundles: List[rs.ResourceSet],
    strategy: str,
    preplaced: Optional[List[Optional[str]]] = None,
    bundle_labels: Optional[List[Optional[Dict[str, str]]]] = None,
) -> Optional[List[Optional[str]]]:
    """Map each bundle to a node id, or None if unplaceable.

    PACK: minimize node count (all on one node if possible).
    SPREAD: spread across distinct nodes, best effort.
    STRICT_PACK: all bundles on a single node or fail — on TPU this is the
    slice-atomic gang (a pjit program's hosts must share an ICI domain).
    STRICT_SPREAD: each bundle on a distinct node or fail.

    `preplaced[i]` pins bundle i to a node it is ALREADY reserved on
    (bundle-granular gang repair: only the holes are placed; preplaced
    bundles' resources are not re-counted — the daemons subtracted them
    at reserve time). `bundle_labels[i]` is a soft per-bundle node-label
    preference (ICI-topology hint): matching nodes are tried first, but
    a non-matching node still satisfies the bundle.
    """
    alive = sorted(view.alive_nodes(),
                   key=lambda n: rs.utilization(n.total, n.available))
    if not alive:
        return None
    preplaced = preplaced or [None] * len(bundles)
    missing = [i for i, nid in enumerate(preplaced) if nid is None]
    if not missing:
        return list(preplaced)

    def try_fit_all_on(node: NodeView) -> bool:
        avail = dict(node.available)
        for i in missing:
            if not rs.fits(avail, bundles[i]):
                return False
            rs.subtract(avail, bundles[i])
        return True

    if strategy in ("PACK", "STRICT_PACK"):
        pinned = {nid for nid in preplaced if nid is not None}
        if strategy == "STRICT_PACK" and pinned:
            # The gang already lives on one node: holes must land there.
            n = view.nodes.get(next(iter(pinned)))
            if n is None or not n.alive or not try_fit_all_on(n):
                return None
            return [n.node_id if nid is None else nid for nid in preplaced]
        for n in alive:
            if try_fit_all_on(n):
                return [n.node_id if nid is None else nid
                        for nid in preplaced]
        if strategy == "STRICT_PACK":
            return None
        # PACK fallback: greedy first-fit over nodes.
        return _greedy(alive, bundles, prefer_distinct=False,
                       preplaced=preplaced, bundle_labels=bundle_labels)

    if strategy in ("SPREAD", "STRICT_SPREAD"):
        placement = _greedy(alive, bundles, prefer_distinct=True,
                            preplaced=preplaced,
                            bundle_labels=bundle_labels,
                            exclusive=(strategy == "STRICT_SPREAD"))
        if placement is None:
            return None
        if strategy == "STRICT_SPREAD" and len(set(placement)) != len(bundles):
            return None
        return placement

    raise ValueError(f"unknown placement strategy {strategy}")


def _labels_match(node: NodeView,
                  selector: Optional[Dict[str, str]]) -> bool:
    if not selector:
        return True
    return all(node.labels.get(k) == v for k, v in selector.items())


def _greedy(nodes: List[NodeView], bundles: List[rs.ResourceSet],
            prefer_distinct: bool,
            preplaced: Optional[List[Optional[str]]] = None,
            bundle_labels: Optional[List[Optional[Dict[str, str]]]] = None,
            exclusive: bool = False) -> Optional[List[Optional[str]]]:
    avail = {n.node_id: dict(n.available) for n in nodes}
    preplaced = preplaced or [None] * len(bundles)
    placement: List[Optional[str]] = list(preplaced)
    used_nodes: set = {nid for nid in preplaced if nid is not None}
    for i, b in enumerate(bundles):
        if placement[i] is not None:
            continue
        chosen = None
        sel = bundle_labels[i] if bundle_labels else None
        candidates = sorted(
            nodes, key=lambda n: (n.node_id in used_nodes
                                  if prefer_distinct else False,
                                  not _labels_match(n, sel),
                                  rs.utilization(n.total, avail[n.node_id])))
        for n in candidates:
            if exclusive and n.node_id in used_nodes:
                continue  # STRICT: preplaced/used nodes are off limits
            if rs.fits(avail[n.node_id], b):
                chosen = n
                break
        if chosen is None:
            return None
        rs.subtract(avail[chosen.node_id], b)
        used_nodes.add(chosen.node_id)
        placement[i] = chosen.node_id
    return placement
