"""Node-side runtime-env builder (the runtime-env agent's job in the
reference, which fate-shares with the raylet — here it lives inside the
node daemon; ref: python/ray/runtime_env/ARCHITECTURE.md,
_private/runtime_env/{pip.py,working_dir.py,uri_cache.py}).

Builds are cached by spec hash under `<base>/<hash>/`:
    pkg/<uri-digest>/  extracted working_dir / py_modules archives
    venv/              --system-site-packages venv when pip reqs exist
    READY              marker: build completed

`ensure_env` returns everything `_spawn_worker` needs: env vars, the
python executable, sys.path prepends, and the worker cwd.
"""
from __future__ import annotations

import asyncio
import io
import logging
import os
import shutil
import subprocess
import sys
import zipfile
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.runtime_env import PKG_NAMESPACE, env_hash

logger = logging.getLogger(__name__)

DEFAULT_BASE = "/tmp/ray_tpu_runtime_envs"


class RuntimeEnvBuildError(Exception):
    """Definitive build failure (bad pip spec, missing package): callers
    must fail fast, not retry-rebuild."""


class BuiltEnv:
    def __init__(self, env_vars: Dict[str, str], python: str,
                 pythonpath: List[str], cwd: Optional[str],
                 container: Optional[Tuple[str, List[str], str]] = None):
        self.env_vars = env_vars
        self.python = python
        self.pythonpath = pythonpath
        self.cwd = cwd
        # Container plugin: (runtime, run_options, image).
        self.container = container

    def wrap_command(self, cmd: List[str], env: Dict[str, str],
                     name: Optional[str] = None) -> List[str]:
        """Wrap the worker argv in `podman/docker run`. env/cwd given to
        Popen only reach the container CLIENT process — everything the
        worker needs must ride -e/-w/-v flags (ref: container.py's
        podman command assembly). `name` makes the container killable by
        the daemon (`podman kill <name>`) — signalling the client process
        does NOT stop the container."""
        if not self.container:
            return cmd
        runtime, run_options, image = self.container
        flags: List[str] = []
        if name:
            flags += ["--name", name]
        # The package checkout must exist at the same path inside.
        import ray_tpu as _rt

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_rt.__file__)))
        flags += ["-v", f"{pkg_root}:{pkg_root}"]
        # Every framework knob resolves from RAY_TPU_* env (config.py);
        # non-container workers inherit ALL of os.environ — forward the
        # same configuration surface, not a hand-picked subset.
        for key, val in env.items():
            if key.startswith("RAY_TPU_") or key in ("PYTHONPATH",
                                                     "JAX_PLATFORMS"):
                flags += ["-e", f"{key}={val}"]
        for k, v in self.env_vars.items():
            flags += ["-e", f"{k}={v}"]
        if self.cwd:
            flags += ["-v", f"{self.cwd}:{self.cwd}", "-w", self.cwd]
        return [runtime, "run", "--rm", "--network=host",
                "-v", "/dev/shm:/dev/shm", "-v", "/tmp:/tmp",
                *flags, *run_options, image] + cmd


class RuntimeEnvBuilder:
    FAILURE_TTL_S = 120.0

    def __init__(self, gcs_client, base_dir: str = DEFAULT_BASE):
        self._gcs = gcs_client
        self._base = base_dir
        self._built: Dict[str, BuiltEnv] = {}
        self._building: Dict[str, asyncio.Future] = {}
        # Negative cache: a failed build is not retried for FAILURE_TTL_S
        # (each attempt can cost a full venv + pip run).
        self._failed: Dict[str, Tuple[float, str]] = {}

    async def ensure_env(self, env: Optional[dict]) -> Optional[BuiltEnv]:
        import time

        if not env:
            return None
        key = env_hash(env)
        cached = self._built.get(key)
        if cached is not None:
            return cached
        failed = self._failed.get(key)
        if failed is not None:
            ts, msg = failed
            if time.monotonic() - ts < self.FAILURE_TTL_S:
                raise RuntimeEnvBuildError(msg)
            del self._failed[key]
        fut = self._building.get(key)
        if fut is not None:
            return await fut  # someone else is building it
        fut = asyncio.get_running_loop().create_future()
        self._building[key] = fut
        try:
            built = await self._build(key, env)
            self._built[key] = built
            fut.set_result(built)
            return built
        except asyncio.CancelledError:
            # RPC deadline/cancellation mid-build is NOT a build verdict:
            # don't poison the negative cache for a valid (just slow) env.
            fut.cancel()
            raise
        except Exception as e:  # noqa: BLE001
            msg = f"runtime_env build failed: {e}"
            self._failed[key] = (time.monotonic(), msg)
            err = RuntimeEnvBuildError(msg)
            fut.set_exception(err)
            # Consume the exception for waiters that never came.
            fut.exception()
            raise err from e
        finally:
            del self._building[key]

    # -- build steps ---------------------------------------------------
    async def _fetch_pkg(self, uri: str, dest: str) -> str:
        """Extract pkg://<digest> from the GCS KV into dest (cached)."""
        target = os.path.join(dest, uri.split("://", 1)[1])
        if os.path.isdir(target):
            return target
        blob = await self._gcs.call("KV", "get",
                                    namespace=PKG_NAMESPACE,
                                    key=uri.encode(), timeout=60)
        if blob is None:
            raise RuntimeError(f"runtime_env package {uri} not found in GCS")
        def extract():
            # Off-loop: a large archive would otherwise stall heartbeats
            # and lease granting for the whole decompression.
            tmp = target + ".tmp"
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp, exist_ok=True)
            with zipfile.ZipFile(io.BytesIO(blob)) as z:
                z.extractall(tmp)
            os.rename(tmp, target)

        await asyncio.get_running_loop().run_in_executor(None, extract)
        return target

    async def _build(self, key: str, env: dict) -> BuiltEnv:
        root = os.path.join(self._base, key)
        os.makedirs(root, exist_ok=True)
        # Cross-process exclusion: multiple daemons on one host share the
        # cache dir; concurrent extracts/venv builds of the same key would
        # corrupt each other. flock taken in a thread (it blocks).
        import fcntl

        lockf = open(os.path.join(self._base, f".{key}.lock"), "w")
        await asyncio.get_running_loop().run_in_executor(
            None, fcntl.flock, lockf, fcntl.LOCK_EX)
        try:
            return await self._build_locked(root, env)
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)
            lockf.close()

    async def _build_locked(self, root: str, env: dict) -> BuiltEnv:
        env_vars = dict(env.get("env_vars") or {})
        pythonpath: List[str] = []
        cwd: Optional[str] = None
        python = sys.executable

        pkg_dir = os.path.join(root, "pkg")
        os.makedirs(pkg_dir, exist_ok=True)
        wd = env.get("working_dir")
        if wd:
            cwd = await self._fetch_pkg(wd, pkg_dir)
            pythonpath.append(cwd)
        for uri in env.get("py_modules") or ():
            mod_dir = await self._fetch_pkg(uri, pkg_dir)
            pythonpath.append(mod_dir)

        reqs = env.get("pip")
        if reqs:
            python = await self._build_venv(root, reqs)
        conda = env.get("conda")
        if conda:
            python = await self._build_conda(root, conda)
        def merge_env(add: Dict[str, str]) -> None:
            # XLA_FLAGS accumulate (node-process flags + user flags +
            # profiling dump + plugin flags must coexist — the built
            # value OVERWRITES the inherited one at spawn, so the
            # inherited flags must be folded in here); everything else
            # overwrites.
            if "XLA_FLAGS" in add:
                base = (env_vars.get("XLA_FLAGS")
                        or os.environ.get("XLA_FLAGS"))
                if base:
                    add = dict(add)
                    add["XLA_FLAGS"] = base + " " + add["XLA_FLAGS"]
            env_vars.update(add)

        prof = env.get("tpu_profiling")
        if prof:
            from ray_tpu.runtime_env import profiling_env_vars

            merge_env(profiling_env_vars(prof))
        for path, value in (env.get("plugins") or {}).items():
            from ray_tpu.runtime_env import load_plugin

            # Per-plugin directory: two plugins writing a same-named
            # artifact must not overwrite each other.
            plugin_root = os.path.join(
                root, "plugins", path.replace(":", "_").replace("/", "_"))
            os.makedirs(plugin_root, exist_ok=True)

            def run_plugin(p=path, v=value, r=plugin_root):
                return load_plugin(p).build(v, r)

            try:
                # Off-loop like extract/venv/conda: a slow plugin build
                # must not stall heartbeats and lease granting.
                built = await asyncio.get_running_loop().run_in_executor(
                    None, run_plugin)
                # Inside the try: a malformed result (env_vars: None,
                # non-dict) must carry the plugin's name, not surface
                # as an anonymous AttributeError.
                add = {str(k): str(v)
                       for k, v in ((built or {}).get("env_vars")
                                    or {}).items()}
            except Exception as e:  # noqa: BLE001
                raise RuntimeEnvBuildError(
                    f"runtime_env plugin {path} failed: {e}") from e
            merge_env(add)
        spec = None
        container = env.get("container")
        if container:
            spec = self._container_spec(container)
        return BuiltEnv(env_vars, python, pythonpath, cwd,
                        container=spec)

    # -- conda plugin (ref: _private/runtime_env/conda.py) -------------
    def _conda_exe(self) -> str:
        # lint: allow-knob -- host toolchain discovery in the agent daemon, not a cluster knob
        exe = os.environ.get("RAY_TPU_CONDA_EXE") or shutil.which("conda")
        if not exe:
            raise RuntimeEnvBuildError(
                "runtime_env requests conda but no conda executable is "
                "available (set RAY_TPU_CONDA_EXE or install conda)")
        return exe

    async def _build_conda(self, root: str, conda) -> str:
        """Named env: resolve its python. Dict spec: create (cached by
        the env hash, READY marker like the pip venv)."""
        exe = self._conda_exe()
        loop = asyncio.get_running_loop()
        if isinstance(conda, str):
            def resolve():
                out = subprocess.run(
                    [exe, "run", "-n", conda, "python", "-c",
                     "import sys; print(sys.executable)"],
                    capture_output=True, text=True, timeout=120)
                lines = out.stdout.strip().splitlines()
                if out.returncode != 0 or not lines:
                    # Some conda versions swallow child stdout on rc=0 —
                    # either way a clear build error, not an IndexError.
                    raise RuntimeError(
                        f"conda env {conda!r} unusable (rc="
                        f"{out.returncode}): {out.stderr[-800:]}")
                return lines[-1]

            return await loop.run_in_executor(None, resolve)

        env_dir = os.path.join(root, "conda")
        python = os.path.join(env_dir, "bin", "python")
        ready = os.path.join(root, "CONDA_READY")
        if os.path.exists(ready) and os.path.exists(python):
            return python

        def create():
            import json as _json

            shutil.rmtree(env_dir, ignore_errors=True)
            spec_path = os.path.join(root, "environment.json")
            with open(spec_path, "w") as f:
                _json.dump(conda, f)
            out = subprocess.run(
                [exe, "env", "create", "-p", env_dir, "-f", spec_path],
                capture_output=True, text=True, timeout=1800)
            if out.returncode != 0:
                raise RuntimeError(
                    f"conda env create failed: {out.stderr[-2000:]}")
            with open(ready, "w") as f:
                f.write("ok")

        await loop.run_in_executor(None, create)
        return python

    # -- container plugin (ref: _private/runtime_env/container.py) -----
    def _container_spec(self, container: dict
                        ) -> Tuple[str, List[str], str]:
        image = container.get("image")
        if not image:
            raise RuntimeEnvBuildError("container runtime_env needs "
                                       "an 'image'")
        # lint: allow-knob -- host toolchain discovery in the agent daemon, not a cluster knob
        runtime = (os.environ.get("RAY_TPU_CONTAINER_RUNTIME")
                   or shutil.which("podman") or shutil.which("docker"))
        if not runtime:
            raise RuntimeEnvBuildError(
                "runtime_env requests a container but neither podman nor "
                "docker is available (set RAY_TPU_CONTAINER_RUNTIME)")
        return (runtime, [str(o) for o in container.get("run_options",
                                                        ())], str(image))

    async def _build_venv(self, root: str, reqs: List[str]) -> str:
        """--system-site-packages venv + pip install (ref: pip.py builds
        a virtualenv per requirements hash). Runs in a thread; serialized
        per env by ensure_env's in-flight future."""
        venv_dir = os.path.join(root, "venv")
        python = os.path.join(venv_dir, "bin", "python")
        ready = os.path.join(root, "READY")
        if os.path.exists(ready) and os.path.exists(python):
            return python

        def build():
            shutil.rmtree(venv_dir, ignore_errors=True)
            subprocess.run(
                [sys.executable, "-m", "venv", "--system-site-packages",
                 venv_dir],
                check=True, capture_output=True, timeout=300)
            # When this process itself runs inside a venv,
            # --system-site-packages exposes the BASE python's packages,
            # not ours (jax/grpc/setuptools live in the parent venv). A
            # .pth makes the parent's site-packages visible too; venv-local
            # installs still take precedence on sys.path.
            import site

            parent_sites = [p for p in site.getsitepackages()
                            if os.path.isdir(p)]
            vsite = os.path.join(
                venv_dir, "lib",
                f"python{sys.version_info.major}.{sys.version_info.minor}",
                "site-packages")
            with open(os.path.join(vsite, "_raytpu_parent.pth"), "w") as f:
                f.write("\n".join(parent_sites) + "\n")
            out = subprocess.run(
                [python, "-m", "pip", "install", "--no-input",
                 "--disable-pip-version-check", "--no-build-isolation",
                 *reqs],
                capture_output=True, text=True, timeout=600)
            if out.returncode != 0:
                raise RuntimeError(
                    f"pip install failed: {out.stderr[-2000:]}")
            with open(ready, "w") as f:
                f.write("ok")

        await asyncio.get_running_loop().run_in_executor(None, build)
        return python
