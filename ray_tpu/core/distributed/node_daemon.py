"""Node daemon: the per-host raylet equivalent.

Analogue of the reference raylet (ref: src/ray/raylet/node_manager.h:125 —
worker lease protocol, local scheduling, worker pool worker_pool.h:156,
dependency mgmt, PG resource reservation placement_group_resource_manager.h;
object transfer object_manager.h:117). One process per host:

  * registers with the GCS, heartbeats available resources
  * owns the host's shm object store directory and serves chunked pulls
  * spawns/pools worker processes; grants leases against local resources
  * spills tasks to other nodes via the hybrid policy when overloaded
  * reserves/returns placement-group bundles
  * starts dedicated actor workers on GCS request; reports worker deaths
"""
from __future__ import annotations

import asyncio
import logging
import os
import random
import struct
import subprocess
import sys
import tempfile
import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ObjectExistsError, ObjectStore
from ray_tpu.core.distributed import resources as rs
from ray_tpu.core.distributed.rpc import AsyncRpcClient, RpcServer
from ray_tpu.core.distributed.transfer import (
    ChunkSink, chunk_ranges, make_transfer_metrics, plan_broadcast_tree)
from ray_tpu.core.distributed.wire import Raw
from ray_tpu.core.distributed.scheduler import (
    ClusterView, NodeView, pick_feasible_node, pick_node)
from ray_tpu.core.distributed.syncer import (
    NodeSyncer, collect_queued_demand)
from ray_tpu.core.distributed.worker_zygote import (
    ZygoteError, ZygoteHandle, start_zygote)

logger = logging.getLogger(__name__)


class WorkerHandle:
    def __init__(self, proc: subprocess.Popen, worker_id: str,
                 env_key: str = ""):
        self.proc = proc
        self.worker_id = worker_id
        self.address: Optional[str] = None      # set on register
        self.busy = False
        self.actor_id: Optional[str] = None
        self.job_id: Optional[str] = None       # last lease's job (logs)
        self.env_key = env_key        # runtime-env identity of this worker
        # (runtime, container_name) for containerized workers: the Popen
        # is only the podman/docker CLIENT — killing it leaves the
        # container running, so teardown must kill by name.
        self.container: Optional[Tuple[str, str]] = None
        self.last_idle = time.monotonic()
        self.registered = asyncio.Event()

    def kill(self, term: bool = False) -> None:
        """Stop this worker INCLUDING its container, if any."""
        if self.container is not None:
            runtime, name = self.container
            try:
                subprocess.run([runtime, "kill", name],
                               capture_output=True, timeout=20)
            except Exception:  # noqa: BLE001 best effort
                pass
        try:
            (self.proc.terminate if term else self.proc.kill)()
        except Exception:  # noqa: BLE001
            pass


class Lease:
    def __init__(self, lease_id: str, demand: rs.ResourceSet,
                 worker: WorkerHandle,
                 placement: Optional[Tuple[str, int]]):
        self.lease_id = lease_id
        self.demand = demand
        self.worker = worker
        self.placement = placement
        self.granted_at = time.monotonic()


class HangWatchdog:
    """Flags RUNNING attempts that exceeded the hang threshold with no
    progress, auto-capturing ONE rate-limited stack dump per attempt
    (ISSUE 5 tentpole part 3; ref: the reference's `ray stack`-driven
    hang triage, done by hand — here the daemon does the first capture
    automatically). Pure policy: the daemon supplies `dump` (async,
    info -> raw text or None) and `record` (info, text -> None), so
    tests can drive `scan` with synthetic observations."""

    MAX_TRACKED = 4096

    def __init__(self, *, dump, record,
                 threshold_s: Optional[float] = None,
                 min_dump_interval_s: Optional[float] = None):
        self._dump = dump
        self._record = record
        self._threshold_s = threshold_s
        self._min_interval_s = min_dump_interval_s
        # (task_id, attempt) -> dump wall time; one capture per attempt,
        # surviving the attempt's disappearance (a retried attempt gets
        # a NEW attempt number and its own budget).
        self._dumped: Dict[Tuple[str, int], float] = {}
        self._last_dump = 0.0
        self.fired_total = 0

    def _cfg(self) -> Tuple[float, float]:
        cfg = get_config()
        return (self._threshold_s if self._threshold_s is not None
                else cfg.hang_threshold_s,
                self._min_interval_s if self._min_interval_s is not None
                else cfg.hang_dump_min_interval_s)

    async def scan(self, running: List[dict],
                   now: Optional[float] = None) -> int:
        """One pass over the currently running attempts; returns how
        many hung attempts were dumped this pass. An attempt that
        completes under the threshold is simply never seen old enough —
        it can never be flagged."""
        threshold, min_interval = self._cfg()
        if threshold <= 0:
            return 0
        now = time.time() if now is None else now
        fired = 0
        for info in running:
            key = (info.get("task_id"), int(info.get("attempt", 0)))
            st = info.get("start_ts")
            age = 0.0 if st is None else now - float(st)
            if age < threshold or key in self._dumped:
                continue
            if now - self._last_dump < min_interval:
                # Global rate limit: a mass hang must not become a
                # signal storm; the attempt stays eligible next scan.
                continue
            self._last_dump = now
            self._dumped[key] = now
            while len(self._dumped) > self.MAX_TRACKED:
                del self._dumped[next(iter(self._dumped))]
            try:
                raw = await self._dump(info)
            except Exception as e:  # noqa: BLE001 dump is best-effort
                logger.debug("watchdog dump failed: %s", e)
                raw = None
            try:
                self._record(dict(info), raw)
            except Exception:  # noqa: BLE001
                logger.exception("watchdog record failed")
            fired += 1
            self.fired_total += 1
        return fired


class NodeDaemon:
    def __init__(
        self,
        *,
        gcs_address: str,
        host: str = "127.0.0.1",
        port: int = 0,
        node_id: Optional[str] = None,
        num_cpus: Optional[float] = None,
        num_tpus: Optional[float] = None,
        custom_resources: Optional[Dict[str, float]] = None,
        store_dir: Optional[str] = None,
        object_store_memory: int = 0,
        labels: Optional[Dict[str, str]] = None,
    ):
        self.gcs_address = gcs_address
        self.node_id = node_id or uuid.uuid4().hex
        self.server = RpcServer(host, port)
        self.total = rs.detect_node_resources(num_cpus, num_tpus,
                                              custom=custom_resources)
        self.available = dict(self.total)
        self.labels = labels or {}
        # Auto-label with this host's TPU worker id so ICI-aware gangs
        # (tpu_slice_placement_group bundle ordering) can prefer it.
        if "TPU_WORKER_ID" not in self.labels:
            from ray_tpu.core.distributed.accelerators import get_worker_id

            wid = get_worker_id()
            if wid is not None:
                self.labels["TPU_WORKER_ID"] = str(wid)
        self.store_dir = store_dir or f"/dev/shm/raytpu_{self.node_id[:12]}"
        self.store = ObjectStore(self.store_dir,
                                 capacity=object_store_memory or 0)
        # Worker stdout/stderr files live OUTSIDE shm (logs are disk data,
        # ref: session_latest/logs layout, node.py get_logs_dir_path).
        # node_log_dir is the shared helper: workers derive the SAME path
        # from their node_id, so the per-pid stack-dump files rendezvous
        # here without extra spawn plumbing.
        from ray_tpu.util.profiling import node_log_dir

        self.log_dir = node_log_dir(self.node_id)
        os.makedirs(self.log_dir, exist_ok=True)
        self.gcs: Optional[AsyncRpcClient] = None

        self._workers: Dict[str, WorkerHandle] = {}     # worker_id -> handle
        self._idle: deque = deque()                      # idle task workers
        self._leases: Dict[str, Lease] = {}
        self._pg_bundles: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self._lease_waiters: deque = deque()             # asyncio futures
        self._infeasible_waits: Dict[int, rs.ResourceSet] = {}
        self._infeasible_seq = 0
        # Push manager state (ref: push_manager.h:30 — dedup + bounded
        # concurrent pushes; receiving side fills the store directly).
        self._push_inflight: Dict[Tuple[str, bytes], asyncio.Future] = {}
        self._push_sem = asyncio.Semaphore(4)
        # In-flight receives: object_id -> ChunkSink writing straight
        # into the store's mmap (create-then-fill). Chunks may land in
        # any order; the sink seals itself at full coverage, and
        # get_object_chunk can RE-SERVE landed ranges before seal (the
        # broadcast relay pipeline).
        self._recv_partials: Dict[bytes, ChunkSink] = {}
        # Pooled clients to peer daemons (push/relay/broadcast): one
        # multiplexed connection per peer instead of a dial per chunk.
        self._peer_clients: Dict[str, AsyncRpcClient] = {}
        # Cross-host channel rings this daemon hosts or pushes into:
        # path -> {"ch": Channel, "lock": threading.Lock}. The lock
        # serializes channel_push executor threads per ring, which also
        # makes the versioned-write dedupe check sound.
        self._channels: Dict[str, dict] = {}
        self._view = ClusterView()
        # Versioned delta reporter + cluster-view receiver (syncer.py);
        # None when RAY_TPU_SYNCER_ENABLED=0 (legacy full-state
        # heartbeats + 1 Hz list_nodes polling).
        self.syncer: Optional[NodeSyncer] = None
        self._tasks: List[asyncio.Task] = []
        self._soft_limit = int(get_config().num_workers_soft_limit
                               or self.total.get("CPU", 1))
        self._env_builder = None  # RuntimeEnvBuilder, lazy (needs gcs)
        # Worker zygotes, one per runtime-env key (insertion order = LRU;
        # ref: worker_pool.h:347 prestart + forkserver-style templates).
        # NOT in self._workers: the OOM sweep and idle reaping never see
        # them — killing the template would re-cold-start the node.
        self._zygotes: Dict[str, ZygoteHandle] = {}
        # Serve replica gauges: (app, replica) -> {"ts", "gauges"}.
        # Replicas on this node push queue depth / KV-pool occupancy
        # here; the aggregate rides the SYNCER delta to the GCS so the
        # serve controller reads one merged view instead of polling
        # every replica per autoscale decision.
        self._serve_gauges: Dict[tuple, dict] = {}
        # Train-rank gauges: (run, rank) -> {"ts", "gauges"}. Training
        # ranks on this node push cumulative step/phase counters here
        # (train/observability.py GaugePusher); the per-run map rides
        # the syncer delta to the GCS TrainRunState. TTL-swept — but
        # the GCS retains what it saw, so a SIGSTOPped rank stays
        # attributable after it ages out here.
        self._train_gauges: Dict[tuple, dict] = {}
        # Worker-process metric registry dumps: origin -> {"ts", "dump"}.
        # Replicas piggyback theirs on the gauge push, other serve
        # workers (HTTP proxy) use report_metrics; _metrics_dump merges
        # them into the federation payload so worker-side serve series
        # (TTFT/ITL histograms, KV counters) reach the GCS exposition.
        self._worker_metric_dumps: Dict[str, dict] = {}
        self._init_metrics()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    async def start(self) -> int:
        from ray_tpu.core.distributed.rpc import set_caller_identity

        self.server.add_service("NodeDaemon", self)
        port = await self.server.start()
        # GCS load attribution: the daemon's default identity is its
        # scheduling plane (leases, heartbeats, object directory);
        # subsystems acting as a different component (syncer pushes,
        # task-event flushes) pass an explicit per-call `_caller`.
        set_caller_identity(self.node_id, "scheduler")
        self.gcs = AsyncRpcClient(self.gcs_address)
        await self.gcs.call(
            "NodeInfo", "register_node", node_id=self.node_id,
            address=self.server.address, resources=self.total,
            store_dir=self.store_dir, labels=self.labels, timeout=30)
        from ray_tpu.core.distributed.log_monitor import LogMonitor

        self._dead_worker_info: Dict[str, dict] = {}

        def worker_info(worker_id: str) -> dict:
            h = self._workers.get(worker_id)
            if h is None:
                return self._dead_worker_info.get(worker_id, {})
            return {"actor_id": h.actor_id, "job_id": h.job_id,
                    "pid": h.proc.pid}

        self._log_monitor = LogMonitor(self.log_dir, self.node_id,
                                       worker_info)
        if get_config().syncer_enabled:
            self.syncer = NodeSyncer(
                gcs=self.gcs, node_id=self.node_id,
                collect=self._syncer_state,
                on_reregister=self._re_register,
                # Metrics federation: this node's whole registry
                # piggybacks on the sync channel at a slow cadence; the
                # GCS merges all nodes' snapshots into one node-labelled
                # /metrics exposition.
                metrics_provider=self._metrics_dump,
                metrics={
                    "deltas": self._m_sync_deltas,
                    "suppressed": self._m_sync_suppressed,
                    "bytes": self._m_sync_bytes,
                    "full_syncs": self._m_sync_full,
                    "keepalives": self._m_sync_keepalives,
                })
        # Daemon-side task-event buffer: the hung-task watchdog's
        # auto-captured dumps ride the SAME bounded ring/drop accounting
        # as executor records (task_events.py).
        from ray_tpu.core.distributed.task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer(
            flush_fn=self._flush_task_events, node_id=self.node_id,
            pid=os.getpid())
        self._watchdog = HangWatchdog(
            dump=self._watchdog_dump, record=self._watchdog_record)
        self._tasks = [
            asyncio.ensure_future(self._heartbeat_loop()),
            asyncio.ensure_future(self._monitor_workers_loop()),
            asyncio.ensure_future(self._refresh_view_loop()),
            asyncio.ensure_future(self._memory_monitor_loop()),
            asyncio.ensure_future(self._log_monitor.run(self.gcs)),
            asyncio.ensure_future(self.task_events.flush_loop()),
            asyncio.ensure_future(self._hang_watchdog_loop()),
        ]
        if self.syncer is not None:
            self._tasks += [
                asyncio.ensure_future(self.syncer.report_loop()),
                asyncio.ensure_future(
                    self.syncer.subscribe_loop(self._view)),
            ]
        self._start_metrics_http()
        if get_config().zygote_enabled:
            # Eager default-env zygote: its interpreter boot + preload
            # overlaps daemon idle time, so the first lease already forks.
            self._ensure_zygote("", None)
        logger.info("node daemon %s on %s (resources=%s store=%s)",
                    self.node_id[:8], self.server.address, self.total,
                    self.store_dir)
        return port

    async def stop(self):
        srv = getattr(self, "_metrics_http", None)
        if srv is not None:
            srv.shutdown()
        for t in self._tasks:
            t.cancel()
        for w in list(self._workers.values()):
            try:
                w.kill()
            except Exception:  # noqa: BLE001
                pass
        for zh in list(self._zygotes.values()):
            zh.kill()
        self._zygotes.clear()
        for sink in list(self._recv_partials.values()):
            try:
                sink.abort()
            except Exception:  # noqa: BLE001
                pass
        self._recv_partials.clear()
        for client in list(self._peer_clients.values()):
            try:
                await client.close()
            except Exception:  # noqa: BLE001
                pass
        self._peer_clients.clear()
        for ent in list(self._channels.values()):
            try:
                ent["ch"].close()
                ent["ch"].unlink()
            except Exception:  # noqa: BLE001
                pass
        self._channels.clear()
        await self.server.stop()
        self.store.disconnect()
        ObjectStore.destroy(self.store_dir)

    def _syncer_state(self) -> Dict[str, Any]:
        """Local versioned view the syncer diffs + ships: resources,
        queued load, object-store stats, worker-pool depth (ref: the
        raylet's RESOURCE_VIEW sync message, ray_syncer.proto:62)."""
        busy = sum(1 for h in self._workers.values() if h.busy)
        return {
            "available": dict(self.available),
            "queued": collect_queued_demand(self._lease_waiters,
                                            self._infeasible_waits),
            "store_used": self.store.used,
            "store_objects": self.store.num_objects,
            "spilled_bytes": self.store.spilled_bytes,
            "workers": len(self._workers),
            "idle_workers": len(self._idle),
            "busy_workers": busy,
            "serve": self._serve_state(),
            "train": self._train_state(),
        }

    def _serve_state(self) -> Dict[str, Any]:
        """Per-app aggregate of this node's replica gauges (TTL-swept so
        a dead replica's numbers stop counting).  Values are rounded so
        tiny float jitter doesn't defeat the syncer's delta suppression."""
        ttl = get_config().serve_gauge_ttl_s
        now = time.monotonic()
        apps: Dict[str, Dict[str, float]] = {}
        for key, ent in list(self._serve_gauges.items()):
            if now - ent["ts"] > ttl:
                del self._serve_gauges[key]
                # Drop the dead replica's mirrored gauge rows too —
                # a stale exposition row is worse than a missing one.
                mirror = getattr(self, "_m_serve_gauge", None)
                for name in ent["gauges"]:
                    if mirror is not None:
                        mirror.remove({"app": key[0], "replica": key[1],
                                       "gauge": name})
                continue
            app = key[0]
            agg = apps.setdefault(app, {"replicas": 0.0})
            agg["replicas"] += 1
            for name, val in ent["gauges"].items():
                try:
                    agg[name] = round(agg.get(name, 0.0) + float(val), 3)
                except (TypeError, ValueError):
                    continue
            # Per-replica disagg state (role, published prefix digests)
            # rides the same TTL sweep: a SIGKILLed replica's registry
            # entries stop routing within serve_gauge_ttl_s.
            if ent.get("state"):
                agg.setdefault("_replicas", {})[key[1]] = ent["state"]
        return apps

    def _train_state(self) -> Dict[str, Any]:
        """Per-run map of this node's training-rank gauges, keyed
        run -> "rank@attempt" (ranks are NOT summed — the GCS skew
        computation needs each rank's step window separately). TTL-swept
        so a finished run's counters stop shipping; the push timestamp
        rides along as `ts_age_s` so the GCS can spot a rank that went
        quiet (SIGSTOP) before the TTL reaps it."""
        ttl = get_config().train_obs_gauge_ttl_s
        now = time.monotonic()
        runs: Dict[str, Dict[str, dict]] = {}
        for key, ent in list(self._train_gauges.items()):
            age = now - ent["ts"]
            if age > ttl:
                del self._train_gauges[key]
                continue
            run, rank = key
            g = dict(ent["gauges"])
            g["ts_age_s"] = round(age, 1)
            runs.setdefault(run, {})[f"{rank}@{g.get('attempt', 0)}"] = g
        return runs

    async def report_train_gauges(self, run: str, rank: int,
                                  gauges: Dict[str, Any],
                                  metrics: Optional[list] = None) -> dict:
        """Training rank -> local daemon gauge push (the train-plane
        leg of the syncer federation; ranks never talk to the GCS).
        The optional `metrics` registry dump piggybacks the rank's
        raytpu_train_* histograms into the node's federation payload,
        same as serve replicas."""
        self._train_gauges[(run, int(rank))] = {
            "ts": time.monotonic(), "gauges": dict(gauges)}
        if metrics is not None:
            self._worker_metric_dumps[f"train:{run}:{rank}"] = {
                "ts": time.monotonic(), "dump": metrics}
        if self.syncer is not None:
            self.syncer.mark_dirty()
        return {"ok": True}

    async def report_serve_gauges(self, app: str, replica: str,
                                  gauges: Dict[str, float],
                                  metrics: Optional[list] = None,
                                  state: Optional[dict] = None) -> dict:
        """Replica -> local daemon gauge push (the serve-autoscaling
        leg of the syncer plane; replicas never talk to the GCS).

        Each gauge is also mirrored into this daemon's own registry as
        raytpu_serve_replica_gauge{app,replica,gauge} so the engine
        gauges appear verbatim in the federated exposition, and the
        optional `metrics` registry dump piggybacks into
        _metrics_dump's merge (histograms/counters the replica process
        records).  `state` carries non-additive per-replica facts —
        disagg role and published prefix digests — surfaced under the
        app's `_replicas` submap instead of the float aggregation."""
        self._serve_gauges[(app, replica)] = {
            "ts": time.monotonic(), "gauges": dict(gauges),
            "state": dict(state) if state else None}
        for name, val in gauges.items():
            try:
                self._m_serve_gauge.set(float(val), {
                    "app": app, "replica": replica, "gauge": name})
            except (TypeError, ValueError):
                continue
        if metrics is not None:
            self._worker_metric_dumps[f"replica:{replica}"] = {
                "ts": time.monotonic(), "dump": metrics}
        if self.syncer is not None:
            self.syncer.mark_dirty()
        return {"ok": True}

    async def report_metrics(self, origin: str, dump: list) -> dict:
        """Generic worker -> local daemon metrics push (serve HTTP
        proxy and friends): the dump is merged into this node's
        federation payload under the node's label, TTL-swept so a dead
        worker's series age out."""
        self._worker_metric_dumps[str(origin)] = {
            "ts": time.monotonic(), "dump": dump}
        return {"ok": True}

    async def _re_register(self) -> None:
        """(Re-)register this node and force the syncer to full-resync —
        the GCS forgot us (restart) or marked us dead (stale verdict)."""
        await self.gcs.call(
            "NodeInfo", "register_node", node_id=self.node_id,
            address=self.server.address, resources=self.total,
            store_dir=self.store_dir, labels=self.labels, timeout=10)
        if self.syncer is not None:
            self.syncer.force_full_resync()
            self.syncer.mark_dirty()

    async def _heartbeat_loop(self):
        cfg = get_config()
        base = cfg.health_check_period_ms / 1000 / 2
        cap = max(base, cfg.heartbeat_backoff_cap_s)
        backoff = base
        while True:
            try:
                # Queued demand feeds the autoscaler (ref: the raylet's
                # resource-load report through the syncer): leases waiting
                # on busy local resources plus infeasible-here demands
                # still waiting for a capable node to join the cluster.
                queued = collect_queued_demand(self._lease_waiters,
                                               self._infeasible_waits)
                reply = await self.gcs.call(
                    "NodeInfo", "heartbeat", node_id=self.node_id,
                    available=dict(self.available),
                    queued_demand=queued, timeout=10)
                if not reply.get("registered"):
                    if reply.get("stale"):
                        logger.warning(
                            "GCS verdict: stale node (%s); re-registering "
                            "as a fresh incarnation",
                            reply.get("reason", ""))
                    await self._re_register()
                backoff = base
            except Exception as e:  # noqa: BLE001
                # Capped exponential backoff — a down GCS must not be
                # hammered at full cadence, and the failure must be
                # visible (counter + warning, not a swallowed debug).
                self._m_heartbeat_failures.inc()
                logger.warning("heartbeat failed: %s (retry in %.1fs)",
                               e, backoff)
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2, cap)
                continue
            period = base
            if self.syncer is not None and self.syncer.healthy():
                # Liveness rides the sync stream (pushes + keepalives);
                # this loop degrades to a slow safety-net probe.
                period = base * max(
                    1.0, cfg.syncer_heartbeat_fallback_factor)
            await asyncio.sleep(period)

    async def _refresh_view_once(self) -> None:
        nodes = await self.gcs.call("NodeInfo", "list_nodes", timeout=10)
        fresh = {}
        for n in nodes:
            fresh[n["node_id"]] = NodeView(
                node_id=n["node_id"], address=n["address"],
                total=n["total"], available=n["available"],
                alive=n["alive"], store_dir=n["store_dir"])
        # Mutate in place: the syncer's subscribe loop folds broadcasts
        # into this same ClusterView object.
        self._view.nodes = fresh

    async def _refresh_view_loop(self):
        while True:
            if self.syncer is not None and self.syncer.view_fresh():
                # The spillback view is being fed by the GCS fan-out
                # stream; polling the full node table would be O(nodes)
                # redundant bytes per tick.
                await asyncio.sleep(1.0)
                continue
            try:
                await self._refresh_view_once()
            except Exception:  # noqa: BLE001
                pass
            await asyncio.sleep(1.0)

    # ------------------------------------------------------------------
    # worker pool (ref: worker_pool.h:156)
    # ------------------------------------------------------------------
    async def _built_env(self, runtime_env: Optional[dict]):
        """Build (or fetch cached) node-local runtime env artifacts."""
        if not runtime_env:
            return None
        if self._env_builder is None:
            from ray_tpu.core.distributed.runtime_env_agent import (
                RuntimeEnvBuilder)

            self._env_builder = RuntimeEnvBuilder(self.gcs)
        return await self._env_builder.ensure_env(runtime_env)

    # -- zygote fork path (ref: worker_pool.h:347 PrestartWorkers;
    # worker_zygote.py docstring for the fork-safety contract) ---------
    def _zygote_compatible(self, built_env) -> bool:
        """Fork is only equivalent to a cold spawn when the child would
        run THIS platform's python in this mount namespace."""
        if not get_config().zygote_enabled:
            return False
        if not sys.platform.startswith("linux"):
            return False  # fork+threads semantics unsafe elsewhere
        if built_env is None:
            return True
        if built_env.container:
            return False  # worker lives in another mount/pid namespace
        if built_env.python != sys.executable:
            return False  # conda/venv env: different interpreter binary
        return True

    def _zygote_socket_path(self, env_key: str) -> str:
        return os.path.join(self.log_dir,
                            f"zygote-{env_key or 'default'}.sock")

    def _ensure_zygote(self, env_key: str,
                       built_env) -> Optional[ZygoteHandle]:
        """Running zygote for this runtime-env key, launching (or
        relaunching after a crash) as needed. Non-blocking: the returned
        handle's socket may still be booting."""
        zh = self._zygotes.pop(env_key, None)
        if zh is not None and zh.alive():
            self._zygotes[env_key] = zh     # re-insert: LRU freshest
            return zh
        if zh is not None:
            logger.warning("zygote for env %r died (code %s); relaunching",
                           env_key or "default", zh.proc.returncode)
            zh.kill()
            self._m_zygote_restarts.inc()
        while len(self._zygotes) >= max(1, get_config().zygote_max):
            old_key, old = next(iter(self._zygotes.items()))
            self._zygotes.pop(old_key)
            old.kill()
        env = {}
        cwd = None
        if built_env is not None:
            env.update(built_env.env_vars)
            if built_env.pythonpath:
                from ray_tpu.core.distributed.driver import child_env

                base = child_env().get("PYTHONPATH", "")
                env["PYTHONPATH"] = ":".join(
                    built_env.pythonpath
                    + [p for p in base.split(":") if p])
            cwd = built_env.cwd
        try:
            proc = start_zygote(
                gcs_address=self.gcs_address,
                daemon_address=self.server.address,
                node_id=self.node_id,
                store_dir=self.store_dir,
                socket_path=self._zygote_socket_path(env_key),
                log_path=os.path.join(
                    self.log_dir, f"zygote-{env_key or 'default'}.log"),
                env=env, cwd=cwd,
                preload=get_config().zygote_preload)
        except Exception as e:  # noqa: BLE001
            logger.warning("zygote launch failed: %s", e)
            return None
        zh = ZygoteHandle(proc, self._zygote_socket_path(env_key),
                          env_key=env_key)
        self._zygotes[env_key] = zh
        return zh

    def _try_fork_worker(self, actor_id: Optional[str], built_env,
                         env_key: str) -> Optional[WorkerHandle]:
        zh = self._ensure_zygote(env_key, built_env)
        if zh is None:
            return None
        worker_id = uuid.uuid4().hex
        out = os.path.join(self.log_dir, f"worker-{worker_id}.out")
        err = os.path.join(self.log_dir, f"worker-{worker_id}.err")
        t0 = time.monotonic()
        try:
            proc = zh.fork_worker(
                worker_id, out, err,
                boot_wait=get_config().zygote_boot_wait_s)
        except ZygoteError as e:
            # One strike: a wedged/crashed zygote is replaced on the
            # next _ensure_zygote; THIS spawn cold-starts.
            logger.warning("zygote fork failed (%s); cold-spawning", e)
            self._zygotes.pop(env_key, None)
            zh.kill()
            self._m_zygote_restarts.inc()
            return None
        self._m_fork_latency.observe(time.monotonic() - t0)
        self._m_forked.inc()
        self._m_spawned.inc()
        handle = WorkerHandle(proc, worker_id, env_key=env_key)
        handle.actor_id = actor_id
        self._workers[worker_id] = handle
        return handle

    def _spawn_worker(self, actor_id: Optional[str] = None,
                      built_env=None, env_key: str = "") -> WorkerHandle:
        if self._zygote_compatible(built_env):
            handle = self._try_fork_worker(actor_id, built_env, env_key)
            if handle is not None:
                return handle
        return self._cold_spawn_worker(actor_id, built_env, env_key)

    def _cold_spawn_worker(self, actor_id: Optional[str] = None,
                           built_env=None,
                           env_key: str = "") -> WorkerHandle:
        from ray_tpu.core.distributed.driver import child_env

        worker_id = uuid.uuid4().hex
        env = child_env()
        env["RAY_TPU_WORKER_ID"] = worker_id
        python = sys.executable
        cwd = None
        if built_env is not None:
            env.update(built_env.env_vars)
            if built_env.pythonpath:
                env["PYTHONPATH"] = ":".join(
                    built_env.pythonpath
                    + [p for p in env.get("PYTHONPATH", "").split(":") if p])
            python = built_env.python
            cwd = built_env.cwd
        cmd = [
            python, "-m", "ray_tpu.core.distributed.worker_main",
            "--gcs-address", self.gcs_address,
            "--daemon-address", self.server.address,
            "--node-id", self.node_id,
            "--store-dir", self.store_dir,
            "--worker-id", worker_id,
        ]
        container_name = None
        if built_env is not None and built_env.container:
            # Container plugin: the worker runs inside podman/docker;
            # env/cwd must ride the run flags, not Popen's env, and the
            # container is named so teardown can kill IT (killing the
            # client process leaves the container running).
            container_name = f"rtpu-worker-{worker_id[:16]}"
            cmd = built_env.wrap_command(cmd, env, name=container_name)
        # Per-worker log files; the LogMonitor tails them to the GCS
        # (ref: worker stdout/stderr files under session logs,
        # node.py:1042 + log_monitor.py tailing).
        out_f = open(os.path.join(self.log_dir,
                                  f"worker-{worker_id}.out"), "ab")
        err_f = open(os.path.join(self.log_dir,
                                  f"worker-{worker_id}.err"), "ab")
        from ray_tpu.core.distributed.driver import pdeathsig_preexec

        try:
            # die_with_parent: a SIGKILL'd daemon must not orphan its
            # workers (they'd keep serving a dead node's address).
            proc = subprocess.Popen(cmd, env=env, cwd=cwd,
                                    stdout=out_f, stderr=err_f,
                                    preexec_fn=pdeathsig_preexec)
        finally:
            out_f.close()
            err_f.close()
        self._m_spawned.inc()
        self._m_cold_spawned.inc()
        handle = WorkerHandle(proc, worker_id, env_key=env_key)
        handle.actor_id = actor_id
        if container_name is not None:
            handle.container = (built_env.container[0], container_name)
        self._workers[worker_id] = handle
        return handle

    # ------------------------------------------------------------------
    # metrics (ref: src/ray/stats/metric_defs.cc 43 DEFINE_stats; exported
    # to Prometheus via the per-node metrics agent in the reference)
    # ------------------------------------------------------------------
    def _init_metrics(self) -> None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        tags = {"node_id": self.node_id[:12]}
        self._m_serve_gauge = Gauge(
            "raytpu_serve_replica_gauge",
            "Serve replica engine gauges (queue depth, active, KV "
            "occupancy...) mirrored from report_serve_gauges",
            tag_keys=("app", "replica", "gauge")).set_default_tags(tags)
        self._m_leases = Counter(
            "raytpu_leases_granted_total",
            "Worker leases granted by this daemon").set_default_tags(tags)
        self._m_spawned = Counter(
            "raytpu_workers_spawned_total",
            "Worker processes spawned").set_default_tags(tags)
        self._m_workers = Gauge(
            "raytpu_workers", "Live worker processes").set_default_tags(tags)
        self._m_busy = Gauge(
            "raytpu_workers_busy", "Busy workers").set_default_tags(tags)
        self._m_waiters = Gauge(
            "raytpu_lease_waiters",
            "Lease requests queued on resources").set_default_tags(tags)
        self._m_store_used = Gauge(
            "raytpu_object_store_used_bytes",
            "Shm store bytes in use").set_default_tags(tags)
        self._m_store_objects = Gauge(
            "raytpu_object_store_objects",
            "Objects in the shm store").set_default_tags(tags)
        self._m_spilled = Gauge(
            "raytpu_object_store_spilled_bytes",
            "Bytes spilled to disk").set_default_tags(tags)
        self._m_lease_wait = Histogram(
            "raytpu_lease_grant_seconds",
            "Lease request to grant latency",
            boundaries=(0.001, 0.01, 0.1, 1, 10)).set_default_tags(tags)
        self._m_oom_kills = Counter(
            "raytpu_oom_worker_kills_total",
            "Workers killed by the memory monitor").set_default_tags(tags)
        # Zygote / warm-pool subsystem (worker_zygote.py).
        self._m_forked = Counter(
            "raytpu_workers_forked_total",
            "Workers started by zygote fork").set_default_tags(tags)
        self._m_cold_spawned = Counter(
            "raytpu_workers_cold_spawned_total",
            "Workers started by cold process spawn").set_default_tags(tags)
        self._m_fork_latency = Histogram(
            "raytpu_zygote_fork_seconds",
            "Zygote fork request latency",
            boundaries=(0.001, 0.005, 0.02, 0.1, 0.5, 2)
        ).set_default_tags(tags)
        self._m_zygote_restarts = Counter(
            "raytpu_zygote_restarts_total",
            "Zygote relaunches after crash/wedge").set_default_tags(tags)
        self._m_prestarted = Counter(
            "raytpu_workers_prestarted_total",
            "Warm workers prestarted against lease backlog"
        ).set_default_tags(tags)
        self._m_pg_prewarmed = Counter(
            "raytpu_pg_prewarmed_workers_total",
            "Warm workers prestarted on pg bundle commit"
        ).set_default_tags(tags)
        self._m_heartbeat_failures = Counter(
            "raytpu_heartbeat_failures_total",
            "Heartbeat RPCs to the GCS that failed").set_default_tags(tags)
        # Diagnosis plane: signal-safe dumps + hung-task watchdog.
        self._m_stack_dumps = Counter(
            "raytpu_stack_dumps_total",
            "Signal-safe worker stack dumps captured").set_default_tags(
            tags)
        self._m_hung = Counter(
            "raytpu_hung_tasks_total",
            "Task attempts flagged hung by the watchdog"
        ).set_default_tags(tags)
        # Cluster-state syncer (syncer.py): the delta/suppressed/bytes
        # trio is what proves the control plane ships deltas, not
        # full-state posts.
        self._m_sync_deltas = Counter(
            "raytpu_syncer_deltas_sent_total",
            "Versioned state deltas pushed to the GCS"
        ).set_default_tags(tags)
        self._m_sync_suppressed = Counter(
            "raytpu_syncer_deltas_suppressed_total",
            "Report ticks suppressed because nothing changed"
        ).set_default_tags(tags)
        self._m_sync_bytes = Counter(
            "raytpu_syncer_bytes_sent_total",
            "Serialized bytes of state pushed to the GCS"
        ).set_default_tags(tags)
        self._m_sync_full = Counter(
            "raytpu_syncer_full_syncs_sent_total",
            "Full snapshot resyncs pushed (connect/reconnect/gap)"
        ).set_default_tags(tags)
        self._m_sync_keepalives = Counter(
            "raytpu_syncer_keepalives_sent_total",
            "Liveness keepalives piggybacked on the sync channel"
        ).set_default_tags(tags)
        # Object transfer plane (transfer.py): in/out chunk bytes prove
        # where data actually moved — the broadcast acceptance check
        # (owner uplink <= fanout*size, not N*size) reads bytes_out.
        self._m_xfer = make_transfer_metrics(tags)
        self._m_xfer_in = self._m_xfer["bytes_in"]
        self._m_xfer_out = self._m_xfer["bytes_out"]

    def _refresh_gauges(self) -> None:
        # Called from HTTP handler threads too: iterate over snapshots,
        # never live dicts the event loop mutates.
        workers = list(self._workers.values())
        self._m_workers.set(
            sum(1 for h in workers if h.proc.poll() is None))
        self._m_busy.set(sum(1 for h in workers if h.busy))
        self._m_waiters.set(len(self._lease_waiters))
        self._m_store_used.set(self.store.used)
        self._m_store_objects.set(self.store.num_objects)
        self._m_spilled.set(self.store.spilled_bytes)

    def get_metrics(self) -> str:
        """Prometheus exposition text; also served over HTTP when
        RAY_TPU_METRICS_EXPORT_PORT is set (ref: metrics agent scrape
        endpoint, dashboard/modules/metrics)."""
        from ray_tpu.util.metrics import get_registry

        self._refresh_gauges()
        return get_registry().prometheus_text()

    def _metrics_dump(self):
        """Structured registry snapshot for the syncer's federation
        piggyback (gauges refreshed first, like the text exposition),
        merged with the TTL-live worker-process dumps pushed via
        report_serve_gauges / report_metrics — counters and histograms
        with identical labelsets sum (several replicas of one app on a
        node aggregate per app), gauges last-write-win."""
        from ray_tpu.util.metrics import merge_dump_lists, registry_dump

        self._refresh_gauges()
        dumps = [registry_dump()]
        ttl = get_config().serve_gauge_ttl_s
        now = time.monotonic()
        for origin, ent in list(self._worker_metric_dumps.items()):
            if now - ent["ts"] > ttl:
                del self._worker_metric_dumps[origin]
                continue
            dumps.append(ent["dump"])
        if len(dumps) == 1:
            return dumps[0]
        return merge_dump_lists(dumps)

    def _start_metrics_http(self) -> None:
        port = get_config().metrics_export_port
        if not port:
            return
        import threading
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        daemon = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                body = daemon.get_metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        try:
            srv = ThreadingHTTPServer(("0.0.0.0", port), Handler)
        except OSError as e:
            logger.warning("metrics HTTP port %d unavailable: %s", port, e)
            return
        self._metrics_http = srv
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        logger.info("metrics exported on :%d/metrics", srv.server_address[1])

    def debug_state(self) -> dict:
        """Scheduler-state snapshot (ref: DebugString dumps the reference
        raylet emits into its logs)."""
        return {
            "total": dict(self.total),
            "available": dict(self.available),
            "leases": len(self._leases),
            "lease_waiters": len(self._lease_waiters),
            "workers": len(self._workers),
            "idle_workers": len(self._idle),
            "busy_workers": sum(1 for h in self._workers.values()
                                if h.busy),
            "pg_bundles": len(self._pg_bundles),
            "pg_bundles_uncommitted": sum(
                1 for b in self._pg_bundles.values()
                if not b.get("committed", True)),
            "zygotes": sum(1 for z in self._zygotes.values()
                           if z.alive()),
            "syncer": (dict(self.syncer.stats,
                            version=self.syncer.version,
                            view_version=self.syncer.view_version)
                       if self.syncer is not None else None),
        }

    def list_workers(self) -> list:
        return [{"worker_id": h.worker_id, "pid": h.proc.pid,
                 "actor_id": h.actor_id, "busy": h.busy,
                 "address": h.address,
                 "alive": h.proc.poll() is None}
                for h in self._workers.values()]

    def kill_worker(self, worker_id: Optional[str] = None,
                    pid: Optional[int] = None) -> dict:
        """Chaos-harness hook (ref: _private/test_utils.py:1560
        WorkerKillerActor): SIGKILL one of this node's workers."""
        for h in self._workers.values():
            if h.worker_id == worker_id or (pid and h.proc.pid == pid):
                try:
                    h.kill()
                except Exception:  # noqa: BLE001
                    return {"ok": False}
                return {"ok": True, "pid": h.proc.pid}
        return {"ok": False}

    def signal_worker(self, sig: int, worker_id: Optional[str] = None,
                      pid: Optional[int] = None) -> dict:
        """Chaos-harness hook: deliver an arbitrary signal to one of
        this node's workers (SIGSTOP makes a deterministic straggler,
        SIGCONT heals it). Only pids the daemon owns are signalable."""
        for h in self._workers.values():
            if h.worker_id == worker_id or (pid and h.proc.pid == pid):
                try:
                    os.kill(h.proc.pid, int(sig))
                except Exception as e:  # noqa: BLE001
                    return {"ok": False, "error": str(e)}
                return {"ok": True, "pid": h.proc.pid}
        return {"ok": False, "error": "no such worker"}

    def kill_random_worker(self, include_actor_workers: bool = False,
                           seed: Optional[int] = None) -> dict:
        rng = random.Random(seed)
        candidates = [
            h for h in self._workers.values()
            if h.proc.poll() is None
            and (include_actor_workers or h.actor_id is None)
        ]
        if not candidates:
            return {"ok": False, "reason": "no candidate workers"}
        victim = rng.choice(candidates)
        try:
            victim.kill()
        except Exception:  # noqa: BLE001
            return {"ok": False}
        return {"ok": True, "pid": victim.proc.pid,
                "worker_id": victim.worker_id}

    async def register_worker(self, worker_id: str, address: str,
                              pid: int) -> dict:
        handle = self._workers.get(worker_id)
        if handle is None:
            return {"ok": False}
        handle.address = address
        handle.registered.set()
        if handle.actor_id is None and not handle.busy:
            if handle not in self._idle:
                # Idleness starts NOW, not at spawn: last_idle was
                # stamped in the constructor, and a slow-registering
                # worker appended with that stale stamp would sit behind
                # younger idlers, breaking _reap_idle_workers' deque-is-
                # idle-ordered assumption (it stops at the first
                # too-young front entry).
                handle.last_idle = time.monotonic()
                self._idle.append(handle)
            self._pump_lease_queue()
        return {"ok": True}

    def _take_idle_worker(self, env_key: str) -> Optional[WorkerHandle]:
        """Pop a live, registered, env-matching idle worker — or None.
        Non-matching idlers keep their front-to-back (longest-idle-
        first) order, same discipline as _get_idle_worker."""
        kept = []
        found = None
        while self._idle:
            handle = self._idle.popleft()
            if (handle.proc.poll() is None and handle.address
                    and not handle.busy):
                if handle.env_key == env_key:
                    found = handle
                    break
                kept.append(handle)
        self._idle.extendleft(reversed(kept))
        return found

    async def _get_idle_worker(self, runtime_env: Optional[dict] = None
                               ) -> WorkerHandle:
        from ray_tpu.runtime_env import env_hash

        env_key = env_hash(runtime_env)
        # Other-env idlers go back to the FRONT in their original order:
        # _reap_idle_workers assumes self._idle[0] is the longest-idle
        # worker, and these were popped from the front.
        found = self._take_idle_worker(env_key)
        if found is not None:
            return found
        built = await self._built_env(runtime_env)
        # Spawn a fresh one and wait for registration — polling the
        # process too: a worker that dies pre-registration (crash, chaos
        # kill) must fail the grant within ~0.1 s, not pin the subtracted
        # resources for the full registration timeout.
        handle = self._spawn_worker(built_env=built, env_key=env_key)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + get_config().worker_register_timeout_s
        while True:
            try:
                await asyncio.wait_for(handle.registered.wait(), timeout=0.1)
                # register_worker appended the new worker to _idle (it
                # cannot know this grant is waiting for it) — claim it
                # back out, or a busy leased worker sits in the idle
                # deque where the reaper/OOM sweep would kill it as
                # expendable.
                try:
                    self._idle.remove(handle)
                except ValueError:
                    pass
                return handle
            except asyncio.TimeoutError:
                if handle.proc.poll() is not None:
                    self._workers.pop(handle.worker_id, None)
                    raise RuntimeError(
                        "worker died before registering") from None
                if loop.time() >= deadline:
                    handle.kill()
                    self._workers.pop(handle.worker_id, None)
                    raise RuntimeError(
                        "worker failed to register in time") from None

    # ------------------------------------------------------------------
    # backlog-driven prestart (ref: worker_pool.h:347 PrestartWorkers)
    # ------------------------------------------------------------------
    def _maybe_prestart_workers(self) -> None:
        """When default-env lease requests queue up, start warm workers
        ahead of the grants: the spawn (fork, ~ms; cold, ~150ms+)
        overlaps the wait for resources instead of following it."""
        cfg = get_config()
        if not cfg.worker_prestart_enabled:
            return
        backlog = sum(1 for (_d, _p, fut, _t, renv) in self._lease_waiters
                      if not renv and not fut.done())
        if backlog < max(1, cfg.zygote_prestart_watermark):
            return
        # Attribute-only scans — no per-handle poll() syscalls: at warm-
        # pool scale this runs against 1k+ handles on every lease, and a
        # dead-but-uncollected handle only overcounts until the monitor
        # loop prunes it (≤1 s), which just delays prestart one beat.
        idle = len(self._idle)
        starting = sum(1 for h in self._workers.values()
                       if h.address is None and h.actor_id is None)
        cap = int(cfg.zygote_warm_pool_cap or self._soft_limit)
        want = min(backlog, cap) - idle - starting
        if want <= 0:
            return
        for _ in range(want):
            try:
                self._spawn_worker()
            except Exception as e:  # noqa: BLE001
                logger.debug("prestart spawn failed: %s", e)
                return
        self._m_prestarted.inc(want)

    def _maybe_refill_warm_pool(self, env_key: str, built_env) -> None:
        """Keep `actor_schedule_concurrency` warm workers ahead of actor
        demand: called on every start_actor, so a creation storm settles
        into pop-warm-worker + async refill — the fork+boot pipeline
        overlaps the NEXT creations instead of serializing inside each
        (ref: worker_pool.h:347 PrestartWorkers, which the reference
        pops actor workers from)."""
        cfg = get_config()
        if not cfg.worker_prestart_enabled:
            return
        depth = min(max(1, cfg.actor_schedule_concurrency),
                    int(cfg.zygote_warm_pool_cap or self._soft_limit))
        # Attribute-only scans (see _maybe_prestart_workers): this runs
        # on EVERY start_actor against every live handle — per-handle
        # poll() syscalls here were a measurable slice of a 1k-actor
        # creation storm on a small host.
        idle = sum(1 for h in self._idle if h.env_key == env_key)
        starting = sum(1 for h in self._workers.values()
                       if h.address is None and h.actor_id is None
                       and h.env_key == env_key)
        want = depth - idle - starting
        if want <= 0:
            return
        for _ in range(want):
            try:
                self._spawn_worker(built_env=built_env, env_key=env_key)
            except Exception as e:  # noqa: BLE001
                logger.debug("warm refill spawn failed: %s", e)
                return
        self._m_prestarted.inc(want)

    async def prestart_workers(self, count: int = 1,
                               runtime_env: Optional[dict] = None) -> dict:
        """Explicit warm-pool fill RPC (the reference exposes the same
        hook as NodeManager PrestartWorkers): start up to `count`
        workers of the given runtime env, bounded by the warm-pool cap."""
        from ray_tpu.runtime_env import env_hash

        built = await self._built_env(runtime_env)
        env_key = env_hash(runtime_env)
        cap = int(get_config().zygote_warm_pool_cap or self._soft_limit)
        idle = len(self._idle)
        started = 0
        for _ in range(max(0, min(int(count), cap - idle))):
            self._spawn_worker(built_env=built, env_key=env_key)
            started += 1
        if started:
            self._m_prestarted.inc(started)
        return {"ok": True, "started": started}

    def flush_idle_workers(self) -> dict:
        """Kill every idle pooled worker (bench/test hook: forces the
        next lease onto the fork-or-cold start path). Zygotes are
        untouched — they are templates, not pool members."""
        killed = 0
        while self._idle:
            handle = self._idle.popleft()
            if handle.busy:
                continue  # mid-grant claim raced in; not idle
            self._workers.pop(handle.worker_id, None)
            self._retire_worker_logs(handle)
            try:
                handle.kill()
            except Exception:  # noqa: BLE001
                pass
            killed += 1
        return {"ok": True, "killed": killed}

    def zygote_state(self) -> dict:
        """Zygote snapshot (tests/tools)."""
        return {"zygotes": [
            {"env_key": k, "pid": zh.proc.pid, "alive": zh.alive(),
             "forks": zh.forks}
            for k, zh in self._zygotes.items()]}

    # ------------------------------------------------------------------
    # memory monitor + OOM killing (ref: memory_monitor.h:52, LIFO-
    # retriable WorkerKillingPolicy worker_killing_policy.h:64)
    # ------------------------------------------------------------------
    @staticmethod
    def _memory_usage_fraction() -> float:
        try:
            import psutil

            return psutil.virtual_memory().percent / 100.0
        except Exception:  # noqa: BLE001
            try:
                info = {}
                with open("/proc/meminfo") as f:
                    for line in f:
                        k, v = line.split(":", 1)
                        info[k] = int(v.strip().split()[0])
                return 1.0 - info["MemAvailable"] / info["MemTotal"]
            except Exception:  # noqa: BLE001
                return 0.0

    async def _memory_monitor_loop(self):
        cfg = get_config()
        period = cfg.memory_monitor_refresh_ms / 1000.0
        if period <= 0:
            return
        while True:
            await asyncio.sleep(period)
            usage = self._memory_usage_fraction()
            if usage > cfg.memory_usage_threshold:
                self.relieve_memory_pressure(usage)

    def relieve_memory_pressure(self, usage: float) -> dict:
        """One sweep under pressure: drop all idle workers, then kill the
        NEWEST leased task worker (LIFO keeps long-running work alive —
        the retried victim loses the least progress; actors are never
        chosen, matching the reference's retriable-first policy).
        Returns what was done (also an RPC for tests/operators)."""
        killed_idle = 0
        while self._idle:
            handle = self._idle.popleft()
            if handle.busy:
                continue  # mid-grant claim raced in; not expendable
            self._workers.pop(handle.worker_id, None)
            try:
                handle.kill()
            except Exception:  # noqa: BLE001
                pass
            killed_idle += 1
        victim = None
        newest = None
        for lease in self._leases.values():
            w = lease.worker
            if w.actor_id is not None or w.proc.poll() is not None:
                continue
            if newest is None or lease.granted_at > newest.granted_at:
                newest = lease
        if newest is not None:
            victim = newest.worker
            logger.warning(
                "memory pressure (%.0f%%): killing newest task worker "
                "%s (lease age %.1fs); the task retries elsewhere",
                usage * 100, victim.worker_id[:8],
                time.monotonic() - newest.granted_at)
            try:
                victim.kill()
            except Exception:  # noqa: BLE001
                pass
            self._m_oom_kills.inc()
        return {"killed_idle": killed_idle,
                "killed_worker": victim.worker_id if victim else None,
                "usage": usage}

    def _reap_idle_workers(self) -> None:
        """Enforce num_workers_soft_limit: idle task workers beyond the
        limit that exceeded the idle-kill threshold are terminated
        (ref: worker_pool idle eviction, worker_pool.h:156 pool semantics)."""
        threshold = (get_config().idle_worker_killing_time_threshold_ms
                     / 1000.0)
        if self._lease_waiters:
            # Queued demand will consume these idlers the moment
            # resources free — reaping them now would just churn spawns
            # against the prestart policy.
            return
        now = time.monotonic()
        n_task_workers = sum(1 for h in self._workers.values()
                             if h.actor_id is None)
        while n_task_workers > self._soft_limit and self._idle:
            handle = self._idle[0]
            if handle.busy:
                self._idle.popleft()  # mid-grant claim raced in
                continue
            if now - handle.last_idle < threshold:
                break  # deque is in idle order; newer ones won't qualify
            self._idle.popleft()
            self._workers.pop(handle.worker_id, None)
            self._retire_worker_logs(handle)
            try:
                handle.kill(term=True)
            except Exception:  # noqa: BLE001
                pass
            n_task_workers -= 1

    def _retire_worker_logs(self, handle: WorkerHandle) -> None:
        """Tombstone attribution for the final tail sweep, then let the
        log monitor drain + unlink the dead worker's files."""
        from ray_tpu.util.profiling import stack_dump_path

        try:  # the dead worker's stack-dump file has no further reader
            os.unlink(stack_dump_path(self.log_dir, handle.proc.pid))
        except OSError:
            pass
        mon = getattr(self, "_log_monitor", None)
        if mon is None:
            return
        self._dead_worker_info[handle.worker_id] = {
            "actor_id": handle.actor_id, "job_id": handle.job_id,
            "pid": handle.proc.pid}
        while len(self._dead_worker_info) > 512:
            self._dead_worker_info.pop(next(iter(self._dead_worker_info)))
        mon.retire(handle.worker_id)

    async def _monitor_workers_loop(self):
        while True:
            # Adaptive cadence: each tick polls EVERY worker handle, so
            # at warm-pool scale (1k+ live workers) the base 0.25 s
            # period alone costs several % of a small host's core in
            # kill(0) probes and dict scans. Death-detection latency
            # degrades to at most 1 s when the pool is huge — the same
            # trade the log monitor makes.
            await asyncio.sleep(
                min(1.0, max(0.25, len(self._workers) / 1000.0)))
            self._reap_idle_workers()
            self._maybe_prestart_workers()
            self._expire_prepared_bundles()
            # Crashed zygotes: drop the handle (and relaunch the
            # default-env one eagerly — it is the hot path for every
            # pool/actor spawn; per-env zygotes relaunch on demand).
            for key, zh in list(self._zygotes.items()):
                if not zh.alive():
                    self._zygotes.pop(key, None)
                    zh.kill()
                    self._m_zygote_restarts.inc()
                    logger.warning(
                        "zygote for env %r exited with code %s",
                        key or "default", zh.proc.returncode)
                    # Eager relaunch for the default-env (hot-path)
                    # zygote, rate-limited so a zygote that dies at
                    # boot (bad preload, unbindable socket) cannot
                    # become a 4 Hz spawn storm — spawns meanwhile
                    # ride the cold fallback.
                    now = time.monotonic()
                    if (key == "" and get_config().zygote_enabled
                            and now - getattr(self, "_zygote_relaunch_ts",
                                              0.0) > 2.0):
                        self._zygote_relaunch_ts = now
                        self._ensure_zygote("", None)
            for wid, handle in list(self._workers.items()):
                if handle.proc.poll() is not None:
                    self._workers.pop(wid, None)
                    self._retire_worker_logs(handle)
                    if handle in self._idle:
                        self._idle.remove(handle)
                    if handle.actor_id is not None:
                        try:
                            await self.gcs.call(
                                "ActorManager", "report_actor_failure",
                                actor_id=handle.actor_id,
                                reason=f"worker process exited with code "
                                       f"{handle.proc.returncode}",
                                timeout=10)
                        except Exception:  # noqa: BLE001
                            pass
                    # Leases held by the dead worker are returned.
                    for lease in list(self._leases.values()):
                        if lease.worker is handle:
                            self._return_lease_internal(lease.lease_id)

    # ------------------------------------------------------------------
    # lease protocol (ref: NodeManager::HandleRequestWorkerLease,
    # node_manager.cc:1696; local dispatch local_task_manager.h:58)
    # ------------------------------------------------------------------
    async def request_lease(self, demand: Dict[str, float],
                            strategy: str = "hybrid",
                            affinity: Optional[str] = None,
                            soft: bool = False,
                            placement: Optional[Tuple[str, int]] = None,
                            runtime_env: Optional[dict] = None,
                            job_id: str = "",
                            parked: bool = False) -> dict:
        reply = await self._request_lease(demand, strategy, affinity, soft,
                                          placement, runtime_env, parked)
        if job_id and reply.get("granted"):
            # Log attribution: worker lines stream to the leasing job's
            # driver (ref: log records carry the worker's job).
            lease = self._leases.get(reply["lease_id"])
            if lease is not None:
                lease.worker.job_id = job_id
        return reply

    async def _request_lease(self, demand: Dict[str, float],
                             strategy: str = "hybrid",
                             affinity: Optional[str] = None,
                             soft: bool = False,
                             placement: Optional[Tuple[str, int]] = None,
                             runtime_env: Optional[dict] = None,
                             parked: bool = False) -> dict:
        cfg = get_config()
        # Placement-group leases draw from the reserved bundle.
        if placement is not None:
            pg_id, bundle_idx = placement
            if bundle_idx < 0:
                bundle_idx = self._find_pg_bundle(pg_id, demand)
                if bundle_idx is None:
                    spill = await self._pg_spill_target(pg_id)
                    if spill:
                        return {"spill_to": spill}
                    return {"granted": False,
                            "error": f"placement group {pg_id[:8]} has no "
                                     f"bundle fitting {demand} here"}
                placement = (pg_id, bundle_idx)
            bundle = self._pg_bundles.get((pg_id, bundle_idx))
            if bundle is not None and not bundle.get("committed", True):
                bundle = None  # prepared-only: unusable until commit
            if bundle is None:
                spill = await self._pg_spill_target(pg_id, bundle_idx)
                if spill:
                    return {"spill_to": spill}
                return {"granted": False,
                        "error": f"bundle {pg_id[:8]}:{bundle_idx} not "
                                 f"reserved on this node"}
            if not rs.fits(bundle["available"], demand):
                return await self._wait_for_lease(demand, placement,
                                                  runtime_env)
            rs.subtract(bundle["available"], demand)
            return await self._grant_safely(demand, placement, runtime_env)

        # Affinity pins to a node.
        if strategy == "node_affinity" and affinity is not None:
            if affinity != self.node_id:
                target = self._view.nodes.get(affinity)
                if target is None:
                    # A node ABSENT from the view may be lag, not death:
                    # the view refreshes at 1 Hz and a lease arriving
                    # right after the target registered fails spuriously
                    # (client retries are fast enough to all land inside
                    # the lag window). Wait out up to ~2 refresh cycles.
                    # An entry that IS present with alive=False is a
                    # GCS-confirmed death — fail immediately, waiting
                    # cannot help. The budget here must stay small: the
                    # soft-affinity fall-through can still enter the
                    # 0.6x-lease-timeout infeasible wait below, and the
                    # combined total must end strictly before the
                    # client's lease RPC timeout (same knob).
                    loop = asyncio.get_running_loop()
                    deadline = loop.time() + min(
                        2.5, 0.2 * cfg.worker_lease_timeout_ms / 1000.0)
                    while loop.time() < deadline:
                        await asyncio.sleep(0.05)
                        target = self._view.nodes.get(affinity)
                        if target is not None:
                            break
                if target is not None and target.alive:
                    return {"spill_to": target.address}
                if not soft:
                    return {"granted": False,
                            "error": f"node {affinity[:8]} not available"}

        if not rs.feasible(self.total, demand):
            # Never runnable here: spill to a feasible node. If none is in
            # view yet, wait for one — the cluster may still be forming or
            # scaling up; the reference queues infeasible tasks rather than
            # failing them (ref: cluster_task_manager.h:42 infeasible queue).
            # The wait must end strictly before the client's lease RPC
            # timeout (same knob) or the error below could never be seen;
            # the background view refresher (1 Hz) supplies fresh state, so
            # this loop only re-reads self._view.
            loop = asyncio.get_running_loop()
            deadline = loop.time() + 0.6 * cfg.worker_lease_timeout_ms / 1000.0
            self._infeasible_seq += 1
            wait_key = self._infeasible_seq
            # Visible to the autoscaler via heartbeats while we wait: this
            # demand is what should trigger a scale-up.
            self._infeasible_waits[wait_key] = demand
            try:
                while True:
                    # A feasible-by-total node takes the request even when
                    # busy right now — its daemon queues the lease until
                    # capacity frees, like the reference's waiting queues.
                    node = pick_feasible_node(self._view, demand,
                                              exclude=self.node_id)
                    if node is not None:
                        return {"spill_to": node.address}
                    if rs.feasible(self.total, demand):
                        break  # dynamic resources appeared locally
                    if loop.time() >= deadline:
                        return {"granted": False,
                                "error": f"no node can satisfy {demand}"}
                    await asyncio.sleep(0.25)
            finally:
                self._infeasible_waits.pop(wait_key, None)

        if rs.fits(self.available, demand):
            rs.subtract(self.available, demand)
            self._ledger("sub:direct", demand)
            return await self._grant_safely(demand, None, runtime_env)

        # Local node busy: consider spilling (hybrid policy). A PARKED
        # request (terminal spill target) queues here instead.
        node = (None if parked else
                pick_node(self._view, demand, strategy=strategy,
                          local_node_id=self.node_id,
                          spread_threshold=cfg.scheduler_spread_threshold))
        if node is not None and node.node_id != self.node_id:
            return {"spill_to": node.address}
        if strategy == "spread" and not parked:
            # SPREAD must not park behind local capacity: the 1 Hz view
            # can lag the local grant that just consumed our CPUs, so
            # pick_node tie-breaks to the (apparently idle) local node —
            # and parked waiters only re-pump on LOCAL release, so a
            # burst of spread tasks serializes on one node while the
            # rest of the cluster idles. Any other fitting node beats
            # waiting here. `park: True` makes the spill terminal: the
            # target queues the request rather than re-spilling on ITS
            # stale view (no ping-pong between busy nodes).
            others = [n for n in self._view.alive_nodes()
                      if n.node_id != self.node_id
                      and rs.fits(n.available, demand)]
            if others:
                # UNIFORM choice, not least-utilized-first: a burst of
                # waiters all consulting the same stale view would pile
                # onto one "least utilized" target and serialize there.
                return {"spill_to": random.choice(others).address,
                        "park": True}
        return await self._wait_for_lease(demand, None, runtime_env)

    async def _wait_for_lease(self, demand, placement,
                              runtime_env=None) -> dict:
        fut = asyncio.get_running_loop().create_future()
        self._lease_waiters.append((demand, placement, fut,
                                    time.monotonic(), runtime_env))
        self._maybe_prestart_workers()
        return await fut

    async def _grant_safely(self, demand, placement,
                            runtime_env=None) -> dict:
        """_grant shielded against RPC cancellation: a client that gives
        up (deadline) mid-grant must not leak the subtracted resources or
        the leased worker (the orphaned lease starves the node forever)."""
        task = asyncio.ensure_future(
            self._grant(demand, placement, runtime_env))
        try:
            return await asyncio.shield(task)
        except asyncio.CancelledError:
            def undo(t):
                try:
                    reply = t.result()
                except BaseException:  # noqa: BLE001 _grant rolled back
                    return
                if reply.get("granted"):
                    self._return_lease_internal(reply["lease_id"])
                else:
                    # grant failed after our subtraction was rolled back
                    # inside _grant — nothing else to undo.
                    pass
            task.add_done_callback(undo)
            raise

    def _pump_lease_queue(self) -> None:
        """Grant queued lease requests that now fit (FIFO with skip)."""
        if not self._lease_waiters:
            return
        remaining = deque()

        async def grant_later(demand, placement, fut, runtime_env):
            try:
                reply = await self._grant(demand, placement, runtime_env)
            except Exception as e:  # noqa: BLE001
                if not fut.done():
                    fut.set_exception(e)
                return
            if fut.done():
                # Waiter cancelled (client deadline) while we granted:
                # undo, or the lease + resources leak forever.
                if reply.get("granted"):
                    self._return_lease_internal(reply["lease_id"])
            else:
                fut.set_result(reply)

        while self._lease_waiters:
            (demand, placement, fut, queued_at,
             runtime_env) = self._lease_waiters.popleft()
            if fut.done():
                continue
            ok = False
            if placement is not None:
                bundle = self._pg_bundles.get(tuple(placement))
                if (bundle is not None and bundle.get("committed", True)
                        and rs.fits(bundle["available"], demand)):
                    rs.subtract(bundle["available"], demand)
                    ok = True
            elif rs.fits(self.available, demand):
                rs.subtract(self.available, demand)
                self._ledger("sub:pump", demand)
                ok = True
            if ok:
                self._m_lease_wait.observe(time.monotonic() - queued_at)
                asyncio.ensure_future(
                    grant_later(demand, placement, fut, runtime_env))
            else:
                remaining.append((demand, placement, fut, queued_at,
                                  runtime_env))
        self._lease_waiters = remaining

    async def _grant(self, demand, placement, runtime_env=None) -> dict:
        from ray_tpu.core.distributed.runtime_env_agent import (
            RuntimeEnvBuildError)

        try:
            worker = await self._get_idle_worker(runtime_env)
        except RuntimeEnvBuildError as e:
            # Definitive: a broken runtime_env spec will not fix itself —
            # the client must fail fast, not retry-rebuild for minutes.
            self._release_demand(demand, placement)
            return {"granted": False, "transient": False, "error": str(e)}
        except Exception as e:  # noqa: BLE001
            # Roll back the resource subtraction. Worker-start failures
            # are transient (crash/chaos/slow start) — the resources are
            # back, so the client should retry, not give up.
            self._release_demand(demand, placement)
            return {"granted": False, "transient": True, "error": str(e)}
        worker.busy = True
        lease_id = uuid.uuid4().hex
        self._leases[lease_id] = Lease(lease_id, demand, worker, placement)
        self._m_leases.inc()
        if self.syncer is not None:
            self.syncer.mark_dirty()  # availability changed: sync promptly
        self._ledger(f"grant:{lease_id[:8]}:pid{worker.proc.pid}", demand)
        return {"granted": True, "worker_address": worker.address,
                "lease_id": lease_id, "node_id": self.node_id,
                "daemon_address": self.server.address}

    def _ledger(self, tag: str, demand) -> None:
        import os as _os
        # lint: allow-knob -- debug tracing gate toggled live on a running daemon
        if _os.environ.get("RAY_TPU_LEDGER"):
            import sys as _sys
            print(f"LEDGER {tag} {demand.get('CPU')} avail="
                  f"{self.available.get('CPU')}", file=_sys.stderr,
                  flush=True)

    def _release_demand(self, demand, placement) -> None:
        if placement is not None:
            bundle = self._pg_bundles.get(tuple(placement))
            if bundle is not None:
                rs.add(bundle["available"], demand)
        else:
            rs.add(self.available, demand)
            self._ledger("add:release", demand)

    def return_lease(self, lease_id: str) -> dict:
        self._return_lease_internal(lease_id)
        return {"ok": True}

    def _return_lease_internal(self, lease_id: str) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            self._ledger(f"return-miss:{lease_id[:8]}", {})
            return
        self._ledger(f"return:{lease_id[:8]}", lease.demand)
        self._release_demand(lease.demand, lease.placement)
        worker = lease.worker
        if worker.proc.poll() is None and worker.actor_id is None:
            worker.busy = False
            worker.last_idle = time.monotonic()
            if worker not in self._idle:
                self._idle.append(worker)
        if self.syncer is not None:
            self.syncer.mark_dirty()  # resources freed: sync promptly
        self._pump_lease_queue()

    def pin_lease(self, lease_id: str) -> dict:
        """Pin a granted lease for a pre-leased task lane.

        The lease's resources go back to the pool — a pinned lane worker
        holds 0 resources while alive, exactly the actor model — but the
        worker stays busy/bound: it is never re-leased, never reaped,
        and keeps executing lane frames until `return_lease` unpins it
        (which returns it to the idle pool). The Lease record stays in
        `_leases` with empty demand so the dead-worker sweep's automatic
        lease return needs no special case."""
        lease = self._leases.get(lease_id)
        if lease is None:
            return {"ok": False, "error": f"no such lease {lease_id[:8]}"}
        if lease.worker.proc.poll() is not None:
            return {"ok": False, "error": "worker dead"}
        self._release_demand(lease.demand, lease.placement)
        self._ledger(f"pin:{lease_id[:8]}", lease.demand)
        lease.demand = {}
        if self.syncer is not None:
            self.syncer.mark_dirty()  # resources freed: sync promptly
        self._pump_lease_queue()
        return {"ok": True}

    # ------------------------------------------------------------------
    # cross-host channel endpoints (compiled execution plane): remote
    # writers push serialized ring payloads as raw frames; this daemon
    # lands them in the LOCAL shm ring its readers poll.
    # ------------------------------------------------------------------
    def _channel_entry(self, path: str, capacity: int, n_readers: int,
                       n_slots: int) -> dict:
        from ray_tpu.experimental.channel import Channel

        ent = self._channels.get(path)
        if ent is None:
            ent = {"ch": Channel(path, capacity, n_readers, n_slots),
                   "lock": threading.Lock()}
            self._channels[path] = ent
        return ent

    def channel_create(self, n_readers: int,
                       capacity: Optional[int] = None,
                       n_slots: Optional[int] = None) -> dict:
        """Create a ring on THIS node for readers that live here."""
        from ray_tpu.experimental import channel as chmod

        os.makedirs(self.store_dir, exist_ok=True)
        ch = chmod.Channel.create(
            n_readers, capacity or chmod.DEFAULT_CAPACITY,
            n_slots or chmod.DEFAULT_SLOTS, directory=self.store_dir)
        self._channels[ch.path] = {"ch": ch, "lock": threading.Lock()}
        return {"path": ch.path, "capacity": ch.capacity,
                "n_readers": ch.n_readers, "n_slots": ch.n_slots}

    async def channel_push(self, path: str, capacity: int, n_readers: int,
                           n_slots: int, version: int, data,
                           push_timeout: Optional[float] = None) -> dict:
        """Land one versioned payload in a local ring. Blocks (in an
        executor thread) until the ring has a free slot, so the writer's
        backpressure crosses the RPC hop. `version <= w_seq` is acked
        without writing — the dedupe that makes writer retries safe."""
        from ray_tpu.experimental.channel import (
            ChannelClosedError, ChannelTimeoutError)

        if not os.path.exists(path):
            return {"closed": True}
        ent = self._channel_entry(path, capacity, n_readers, n_slots)
        ch, lock = ent["ch"], ent["lock"]
        version = int(version)

        def _push():
            with lock:
                if version <= ch.version():
                    return {"ok": True, "version": version,
                            "deduped": True}
                ch.write_bytes(data, timeout=push_timeout)
                return {"ok": True, "version": version}

        try:
            return await asyncio.get_running_loop().run_in_executor(
                None, _push)
        except ChannelClosedError:
            return {"closed": True}
        except ChannelTimeoutError:
            return {"timeout": True}
        except Exception as e:  # noqa: BLE001
            return {"error": str(e)}

    def channel_version(self, path: str) -> dict:
        from ray_tpu.experimental.channel import _HDR

        try:
            with open(path, "rb") as f:
                hdr = f.read(_HDR.size)
            _, closed, _, _, _, wseq = _HDR.unpack_from(hdr, 0)
            return {"version": wseq, "closed": bool(closed)}
        except (OSError, struct.error):
            return {"version": 0, "closed": True}

    def channel_close(self, path: str) -> dict:
        """Set the ring's closed flag: every blocked read/write raises."""
        try:
            fd = os.open(path, os.O_RDWR)
            try:
                os.pwrite(fd, struct.pack("<I", 1), 4)
            finally:
                os.close(fd)
            return {"ok": True}
        except OSError as e:
            return {"ok": False, "error": str(e)}

    def channel_unlink(self, path: str) -> dict:
        if "rtpu_chan_" not in os.path.basename(path):
            return {"ok": False, "error": "not a channel path"}
        ent = self._channels.pop(path, None)
        if ent is not None:
            try:
                ent["ch"].unlink()
            except Exception:  # noqa: BLE001
                pass
        try:
            os.unlink(path)
        except OSError:
            pass
        return {"ok": True}

    def _find_pg_bundle(self, pg_id: str, demand) -> Optional[int]:
        for (pid, idx), bundle in self._pg_bundles.items():
            if (pid == pg_id and bundle.get("committed", True)
                    and rs.fits(bundle["available"], demand)):
                return idx
        return None

    async def _pg_spill_target(self, pg_id: str,
                               bundle_idx: Optional[int] = None
                               ) -> Optional[str]:
        """Daemon address of the node hosting this PG bundle (GCS lookup)."""
        try:
            info = await self.gcs.call("PlacementGroups", "get_pg",
                                       pg_id=pg_id, timeout=10)
        except Exception:  # noqa: BLE001
            return None
        if info is None or info["state"] != "CREATED" or not info["nodes"]:
            return None
        if bundle_idx is None or bundle_idx < 0:
            candidates = [n for n in info["nodes"] if n != self.node_id]
            target = candidates[0] if candidates else None
        else:
            target = info["nodes"][bundle_idx] if bundle_idx < len(
                info["nodes"]) else None
        if target is None or target == self.node_id:
            return None
        node = self._view.nodes.get(target)
        if node is not None and node.alive:
            return node.address
        # The 1 Hz view refresher may not have learned the target node yet
        # (races cluster formation); the GCS registry is authoritative.
        try:
            for n in await self.gcs.call("NodeInfo", "list_nodes",
                                         timeout=10):
                if n["node_id"] == target and n["alive"]:
                    return n["address"]
        except Exception:  # noqa: BLE001
            pass
        return None

    # ------------------------------------------------------------------
    # placement groups (ref: placement_group_resource_manager.h)
    # ------------------------------------------------------------------
    def reserve_pg_bundle(self, pg_id: str, bundle_idx: int,
                          resources: Dict[str, float],
                          ttl_s: Optional[float] = None) -> dict:
        """PREPARE phase of the two-phase gang reserve (ref:
        gcs_placement_group_scheduler.h:274 prepare/commit): resources
        leave the pool immediately, but the bundle is unusable (leases
        and actors reject it) until commit_pg_bundle. If the GCS dies or
        a peer node's prepare fails, the TTL sweep returns the resources
        — a half-placed gang can never leak bundles."""
        existing = self._pg_bundles.get((pg_id, bundle_idx))
        if existing is not None:
            # Idempotent re-prepare (GCS retry of a timed-out RPC whose
            # first attempt actually landed): refresh the TTL.
            if not existing["committed"]:
                existing["expires_at"] = time.monotonic() + float(
                    ttl_s or get_config().pg_prepare_ttl_s)
            return {"ok": True}
        if not rs.fits(self.available, resources):
            return {"ok": False, "error": "insufficient resources"}
        rs.subtract(self.available, resources)
        self._pg_bundles[(pg_id, bundle_idx)] = {
            "resources": dict(resources),
            "available": dict(resources),
            "committed": False,
            "expires_at": time.monotonic() + float(
                ttl_s or get_config().pg_prepare_ttl_s),
        }
        return {"ok": True}

    def commit_pg_bundle(self, pg_id: str, bundle_idx: int) -> dict:
        """COMMIT phase: the whole gang prepared, so this bundle becomes
        usable (and permanent until returned). Pre-warms one pool worker
        so the gang's actor/lease start rides a zygote fork."""
        bundle = self._pg_bundles.get((pg_id, bundle_idx))
        if bundle is None:
            # Prepared bundle already expired or was rolled back — the
            # GCS must treat the gang as failed and retry from scratch.
            return {"ok": False, "error": "bundle not prepared"}
        bundle["committed"] = True
        bundle["expires_at"] = None
        self._maybe_prewarm_for_bundle()
        self._pump_lease_queue()
        return {"ok": True}

    def _maybe_prewarm_for_bundle(self) -> None:
        """One warm default-env worker per committed bundle (bounded by
        the warm-pool cap): gang start pops these instead of forking
        inside the critical path."""
        cfg = get_config()
        if not (cfg.pg_prewarm_enabled and cfg.worker_prestart_enabled):
            return
        idle = len(self._idle)
        starting = sum(1 for h in self._workers.values()
                       if h.address is None and h.actor_id is None)
        cap = int(cfg.zygote_warm_pool_cap or self._soft_limit)
        if idle + starting >= cap:
            return
        try:
            self._spawn_worker()
        except Exception as e:  # noqa: BLE001
            logger.debug("pg prewarm spawn failed: %s", e)
            return
        self._m_prestarted.inc()
        self._m_pg_prewarmed.inc()

    def _expire_prepared_bundles(self) -> None:
        """TTL backstop for the prepare phase (runs from the monitor
        loop): uncommitted bundles whose GCS never came back roll back
        on their own."""
        now = time.monotonic()
        for key, bundle in list(self._pg_bundles.items()):
            exp = bundle.get("expires_at")
            if bundle.get("committed") or exp is None or now < exp:
                continue
            self._pg_bundles.pop(key, None)
            rs.add(self.available, bundle["resources"])
            logger.warning("prepared pg bundle %s:%d expired after "
                           "%.1fs without commit; resources returned",
                           key[0][:8], key[1],
                           get_config().pg_prepare_ttl_s)
            self._pump_lease_queue()

    def return_pg_bundle(self, pg_id: str, bundle_idx: int) -> dict:
        bundle = self._pg_bundles.pop((pg_id, bundle_idx), None)
        if bundle is not None:
            rs.add(self.available, bundle["resources"])
            self._pump_lease_queue()
        return {"ok": True}

    # ------------------------------------------------------------------
    # actors
    # ------------------------------------------------------------------
    async def start_actor(self, actor_id: str, cls_blob_key: bytes,
                          args_blob: bytes, demand: Dict[str, float],
                          runtime_env: Optional[dict] = None,
                          max_concurrency: int = 1,
                          concurrency_groups: Optional[Dict[str, int]] = None,
                          placement: Optional[Tuple[str, int]] = None,
                          owner_job: str = "") -> dict:
        if placement is not None:
            placement = tuple(placement)
            bundle = self._pg_bundles.get(placement)
            if (bundle is None or not bundle.get("committed", True)
                    or not rs.fits(bundle["available"], demand)):
                return {"ok": False, "error": "pg bundle unavailable"}
            rs.subtract(bundle["available"], demand)
        else:
            if not rs.fits(self.available, demand):
                return {"ok": False, "error": "insufficient resources"}
            rs.subtract(self.available, demand)

        try:
            built = await self._built_env(runtime_env)
        except asyncio.CancelledError:
            # Client deadline mid-build: roll back and let cancellation
            # propagate — it is not a creation verdict.
            self._release_demand(demand, placement)
            raise
        except Exception as e:  # noqa: BLE001
            self._release_demand(demand, placement)
            return {"ok": False,
                    "error": f"runtime_env build failed: {e}",
                    "creation_error": True}
        from ray_tpu.runtime_env import env_hash

        env_key = env_hash(runtime_env)
        # Warm-pool fast path (ref: the reference pops actor-creation
        # workers from the same pool as task workers): an idle, already-
        # registered worker of the right env skips spawn + registration
        # entirely — actor readiness becomes one create_actor RPC.
        handle = self._take_idle_worker(env_key)
        if handle is not None:
            handle.actor_id = actor_id
        else:
            handle = self._spawn_worker(actor_id=actor_id, built_env=built,
                                        env_key=env_key)
        self._maybe_refill_warm_pool(env_key, built)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + get_config().worker_register_timeout_s
        while not handle.registered.is_set():
            try:
                await asyncio.wait_for(handle.registered.wait(), timeout=0.1)
            except asyncio.TimeoutError:
                if (handle.proc.poll() is not None
                        or loop.time() >= deadline):
                    handle.kill()
                    self._workers.pop(handle.worker_id, None)
                    self._release_demand(demand, placement)
                    return {"ok": False,
                            "error": "actor worker failed to start"}
        handle.busy = True
        handle.job_id = owner_job or handle.job_id
        client = AsyncRpcClient(handle.address)
        try:
            reply = await client.call(
                "Worker", "create_actor", actor_id=actor_id,
                cls_blob_key=cls_blob_key, args_blob=args_blob,
                max_concurrency=max_concurrency,
                concurrency_groups=concurrency_groups,
                timeout=get_config().actor_creation_timeout_s)
        finally:
            await client.close()
        if not reply.get("ok"):
            handle.kill()
            self._workers.pop(handle.worker_id, None)
            self._release_demand(demand, placement)
            return {"ok": False, "error": reply.get("error"),
                    "creation_error": True}
        # Track so the demand is returned if/when the actor dies.
        lease_id = f"actor-{actor_id}"
        self._leases[lease_id] = Lease(lease_id, demand, handle, placement)
        return {"ok": True, "worker_address": handle.address}

    async def kill_worker(self, worker_address: str) -> dict:
        for handle in self._workers.values():
            if handle.address == worker_address:
                handle.kill()
                return {"ok": True}
        return {"ok": False}

    # ------------------------------------------------------------------
    # diagnosis plane: signal-safe stack dumps + hung-task watchdog
    # (profiling.py helpers; the GCS `Diagnosis` service fans
    # dump_worker_stacks out over every daemon)
    # ------------------------------------------------------------------
    async def _flush_task_events(self, **payload) -> None:
        await self.gcs.call("TaskEvents", "add_task_events", timeout=10,
                            _caller=(self.node_id, "task-events"),
                            **payload)

    def _dump_lock(self, pid: int) -> asyncio.Lock:
        """Per-pid dump serialization: concurrent dumps of ONE worker
        would race each other's size-offset bookkeeping."""
        locks = getattr(self, "_dump_locks", None)
        if locks is None:
            locks = self._dump_locks = {}
        if len(locks) > 1024:
            locks.clear()
        return locks.setdefault(pid, asyncio.Lock())

    async def _signal_dump(self, pid: int,
                           timeout_s: float = 3.0) -> dict:
        """Signal-safe stack extraction: SIGUSR1 the worker (its
        faulthandler handler appends an all-thread traceback to the
        per-pid dump file WITHOUT needing the GIL), tail the file, and
        return the new bytes. This is the path that still answers when
        the worker is wedged in a GIL-holding native call — the case
        the in-process sampling `profile` RPC can never see."""
        import signal as _signal

        from ray_tpu.util.profiling import stack_dump_path

        path = stack_dump_path(self.log_dir, pid)
        async with self._dump_lock(pid):
            try:
                pre = os.path.getsize(path)
            except OSError:
                pre = 0
            if pre > (1 << 20):
                # The handler writes with O_APPEND, so truncating the
                # quiescent file is safe — appends land at the new EOF.
                try:
                    os.truncate(path, 0)
                    pre = 0
                except OSError:
                    pass
            try:
                os.kill(pid, _signal.SIGUSR1)
            except ProcessLookupError:
                return {"ok": False, "error": "process gone"}
            except PermissionError as e:
                return {"ok": False, "error": f"signal failed: {e}"}
            self._m_stack_dumps.inc()
            loop = asyncio.get_running_loop()
            deadline = loop.time() + timeout_s
            last = pre
            while loop.time() < deadline:
                await asyncio.sleep(0.05)
                try:
                    size = os.path.getsize(path)
                except OSError:
                    size = pre
                if size > pre and size == last:
                    break       # grew, then stable for one poll: done
                last = size
            if last <= pre:
                return {"ok": False,
                        "error": "no dump produced (worker without a "
                                 "SIGUSR1 faulthandler, or gone)"}
            with open(path, "rb") as f:
                f.seek(pre)
                raw = f.read(min(last - pre, 256 * 1024)).decode(
                    "utf-8", "replace")
        return {"ok": True, "raw": raw}

    async def dump_worker_stacks(self, worker_id: Optional[str] = None,
                                 pids: Optional[List[int]] = None
                                 ) -> dict:
        """All-thread tracebacks of this node's live workers (filtered
        by worker-id prefix and/or pid list), via the signal-safe path.
        Powers `ray-tpu stack` through the GCS Diagnosis fan-out."""
        from ray_tpu.util.profiling import parse_faulthandler_dump

        targets = []
        for h in list(self._workers.values()):
            if h.proc.poll() is not None:
                continue
            if worker_id and not h.worker_id.startswith(worker_id):
                continue
            if pids and h.proc.pid not in pids:
                continue
            targets.append(h)

        async def one(h) -> dict:
            rep = await self._signal_dump(h.proc.pid)
            rep.update(worker_id=h.worker_id, pid=h.proc.pid,
                       actor_id=h.actor_id)
            if rep.get("ok"):
                rep["threads"] = parse_faulthandler_dump(rep["raw"])
            return rep

        workers = list(await asyncio.gather(*(one(h) for h in targets)))
        return {"node_id": self.node_id, "workers": workers}

    async def _hang_watchdog_loop(self):
        cfg = get_config()
        if cfg.hang_threshold_s <= 0:
            return
        # worker_id -> last successful running_tasks snapshot: when a
        # worker stops answering (GIL wedged), the watchdog falls back
        # to the attempts it LAST saw running there.
        self._last_running: Dict[str, List[dict]] = {}
        self._unresponsive: Dict[str, int] = {}
        self._next_poll: Dict[str, float] = {}
        period = max(0.2, cfg.hang_poll_interval_s)
        while True:
            await asyncio.sleep(period)
            try:
                await self._watchdog_tick(period)
            except Exception:  # noqa: BLE001 watchdog must not die
                logger.exception("hang watchdog tick failed")

    async def _watchdog_tick(self, period: float) -> None:
        cfg = get_config()
        # Lazy per-worker cadence: an attempt can't exceed the hang
        # threshold sooner than `threshold` after it starts, so polling
        # each busy worker ~4x per threshold catches every hang within
        # 1.25x threshold while keeping the watchdog O(busy/threshold)
        # RPCs per second — a 1k-actor warm fleet must not cost 1k
        # connects every tick. Cached snapshots keep aging in between.
        repoll = max(period, cfg.hang_threshold_s / 4.0)
        now_m = time.monotonic()
        busy: List[WorkerHandle] = []
        due: List[WorkerHandle] = []
        for h in list(self._workers.values()):
            if (not h.busy or h.address is None
                    or h.proc.poll() is not None):
                self._last_running.pop(h.worker_id, None)
                self._unresponsive.pop(h.worker_id, None)
                self._next_poll.pop(h.worker_id, None)
                continue
            busy.append(h)
            if now_m >= self._next_poll.get(h.worker_id, 0.0):
                self._next_poll[h.worker_id] = now_m + repoll
                due.append(h)

        sem = asyncio.Semaphore(16)

        async def poll(h: WorkerHandle) -> None:
            async with sem:
                client = AsyncRpcClient(h.address)
                try:
                    rep = await client.call("Worker", "running_tasks",
                                            timeout=min(2.0, repoll))
                    self._last_running[h.worker_id] = rep.get("tasks") \
                        or []
                    self._unresponsive.pop(h.worker_id, None)
                except Exception:  # noqa: BLE001 — wedged or mid-
                    # restart: the LAST snapshot still names the
                    # attempt to blame, and the signal-dump path works
                    # regardless of the RPC loop's health.
                    self._unresponsive[h.worker_id] = \
                        self._unresponsive.get(h.worker_id, 0) + 1
                finally:
                    await client.close()

        if due:
            await asyncio.gather(*(poll(h) for h in due))
        running: List[dict] = []
        for h in busy:
            for info in self._last_running.get(h.worker_id) or ():
                info = dict(info)
                info["worker_id"] = h.worker_id
                info["wpid"] = h.proc.pid
                running.append(info)
        await self._watchdog.scan(running)

    async def _watchdog_dump(self, info: dict) -> Optional[str]:
        rep = await self._signal_dump(int(info.get("wpid") or 0))
        return rep.get("raw") if rep.get("ok") else None

    def _watchdog_record(self, info: dict, raw: Optional[str]) -> None:
        """Attach the auto-captured dump to the attempt's task-event
        record (bounded size; rides the daemon buffer's ring/drop
        accounting) and surface the hang in the cluster event log."""
        text = (raw or "")[:get_config().hang_dump_max_bytes] or None
        now = time.time()
        self.task_events.record_status(
            info["task_id"], info.get("attempt", 0), "RUNNING",
            ts=info.get("start_ts"), name=info.get("name"),
            job_id=info.get("job_id"), actor_id=info.get("actor_id"),
            node_id=self.node_id, worker_id=info.get("worker_id"),
            pid=info.get("wpid"), hung=True, hung_stack=text,
            hung_ts=now)
        self._m_hung.inc()
        logger.warning(
            "hung task %s (%s) on worker %s pid=%s: running %.0fs; "
            "stack dump %s", (info.get("task_id") or "")[:12],
            info.get("name"), (info.get("worker_id") or "")[:8],
            info.get("wpid"), now - (info.get("start_ts") or now),
            "captured" if text else "unavailable")

        async def log_event():
            try:
                await self.gcs.call(
                    "EventLog", "add_event", source="task",
                    severity="WARNING",
                    message=f"hung task {info.get('name')} "
                            f"({(info.get('task_id') or '')[:12]}) on "
                            f"node {self.node_id[:8]}: no progress for "
                            f"{now - (info.get('start_ts') or now):.0f}s",
                    fields={"task_id": info.get("task_id"),
                            "node_id": self.node_id,
                            "pid": info.get("wpid")}, timeout=10)
            except Exception:  # noqa: BLE001
                pass

        asyncio.ensure_future(log_event())

    # ------------------------------------------------------------------
    # object plane (transfer.py: raw-frame chunks, create-then-fill
    # receive, striped pulls, broadcast relay tree)
    # ------------------------------------------------------------------
    PEER_CLIENT_CAP = 32

    def _peer_client(self, address: str) -> AsyncRpcClient:
        """Pooled multiplexed connection to a peer daemon (LRU-capped):
        chunk RPCs must not pay a TCP dial per chunk."""
        client = self._peer_clients.pop(address, None)
        if client is None:
            client = AsyncRpcClient(address)
            while len(self._peer_clients) >= self.PEER_CLIENT_CAP:
                _, old = self._peer_clients.popitem()
                asyncio.ensure_future(old.close())
        self._peer_clients[address] = client    # re-insert: LRU freshest
        return client

    def _expire_recv_partials(self) -> None:
        """Abort receives whose sender died mid-transfer — an abandoned
        partial pins its full store reservation, not just RAM."""
        ttl = get_config().transfer_partial_ttl_s
        now = time.monotonic()
        for ob, sink in list(self._recv_partials.items()):
            if now - sink.last_touch > ttl:
                self._recv_partials.pop(ob, None)
                try:
                    sink.abort()
                except Exception:  # noqa: BLE001
                    pass

    def _new_recv_sink(self, object_id: bytes,
                       total_size: int) -> ChunkSink:
        """Create-then-fill receive surface for one incoming object;
        registers the location and drops the partial on completion."""
        oid = ObjectID(object_id)

        def on_complete() -> None:
            self._recv_partials.pop(object_id, None)

            async def register() -> None:
                try:
                    await self.gcs.call(
                        "ObjectDirectory", "add_location",
                        object_id=object_id, node_id=self.node_id,
                        size=total_size, timeout=10)
                except Exception:  # noqa: BLE001
                    pass

            asyncio.ensure_future(register())

        partial = self.store.create_for_receive(oid, total_size)
        sink = ChunkSink(partial, total_size, on_complete=on_complete)
        if not sink.sealed:               # zero-size seals immediately
            self._recv_partials[object_id] = sink
        return sink

    async def push_object(self, object_id: bytes,
                          target_address: str) -> dict:
        """Proactively push a local object into another node's store
        (ref: src/ray/object_manager/push_manager.h:30 — deduplicated,
        bounded-concurrency chunked pushes). Used for pre-staging /
        replication; the pull path stays the default. Chunks ride raw
        frames (wire.Raw memoryviews of the shm mapping) with a small
        pipeline of RPCs in flight toward the receiver."""
        oid = ObjectID(object_id)
        key = (target_address, object_id)
        existing = self._push_inflight.get(key)
        if existing is not None:
            # Dedup shares the in-flight transfer's OUTCOME — a bare
            # "ok" here would report success for a push that then fails.
            return await asyncio.shield(existing)
        buf = self.store.get_buffer(oid)
        if buf is None:
            return {"ok": False, "error": "object not local"}
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._push_inflight[key] = fut
        try:
            async with self._push_sem:
                cfg = get_config()
                total = buf.size
                raw = cfg.transfer_raw_frames
                client = self._peer_client(target_address)
                pending: set = set()
                depth = max(1, cfg.transfer_push_pipeline)
                ranges = (chunk_ranges(
                    total, cfg.object_transfer_chunk_bytes) or [(0, 0)])
                for off, ln in ranges:
                    while len(pending) >= depth:
                        done, pending = await asyncio.wait(
                            pending,
                            return_when=asyncio.FIRST_COMPLETED)
                        for t in done:
                            t.result()   # surface receiver failures
                    view = buf.view[off:off + ln]
                    pending.add(asyncio.ensure_future(client.call(
                        "NodeDaemon", "receive_object_chunk",
                        object_id=object_id, offset=off,
                        total_size=total,
                        data=Raw(view) if raw else bytes(view),
                        last=off + ln >= total, timeout=120)))
                    self._m_xfer_out.inc(ln)
                if pending:
                    done, _ = await asyncio.wait(pending)
                    for t in done:
                        t.result()
            reply = {"ok": True, "bytes": total}
        except Exception as e:  # noqa: BLE001
            reply = {"ok": False, "error": repr(e)}
        finally:
            buf.release()
            self._push_inflight.pop(key, None)
        if not fut.done():
            fut.set_result(reply)
        return reply

    async def receive_object_chunk(self, object_id: bytes, offset: int,
                                   total_size: int, data,
                                   last: bool = False) -> dict:
        """Receiving side of push/relay: chunks land at their offset
        DIRECTLY in the store's mmap (create-then-fill) — the receiver
        heap holds only the in-flight frame, never the object. Order-
        independent: the sink seals on full coverage, not on `last`."""
        oid = ObjectID(object_id)
        self._expire_recv_partials()
        sink = self._recv_partials.get(object_id)
        if sink is None:
            if self.store.contains(oid):
                return {"ok": True, "already": True}
            try:
                sink = self._new_recv_sink(object_id, total_size)
            except ObjectExistsError:
                # Raced in via the pull path / a local put mid-create.
                return {"ok": True, "already": True}
        sink.write(offset, data)
        self._m_xfer_in.inc(len(data))
        return {"ok": True, "sealed": sink.sealed}

    async def get_object_chunk(self, object_id: bytes, offset: int,
                               length: int, wait: bool = False,
                               raw: bool = True) -> dict:
        """Serve one chunk as a raw frame — a memoryview straight off
        the shm mapping, zero copies on this side (the legacy bytes()
        path survives only for raw=False / kill-switch callers). Serves
        from an in-flight partial too when the range has landed
        (`wait=True` long-polls for it): broadcast children stream an
        object out of this daemon while it is still arriving."""
        oid = ObjectID(object_id)
        use_raw = raw and get_config().transfer_raw_frames
        buf = self.store.get_buffer(oid)
        if buf is None:
            sink = self._recv_partials.get(object_id)
            if sink is not None:
                end = min(offset + length, sink.size)
                have = sink.has(offset, end)
                if not have and wait:
                    have = await sink.wait_range(
                        offset, end,
                        get_config().transfer_chunk_timeout_s)
                if sink.sealed:
                    buf = self.store.get_buffer(oid)   # serve sealed
                elif have:
                    view = sink.read(offset, end)
                    self._m_xfer_out.inc(end - offset)
                    return {"total_size": sink.size,
                            "data": Raw(view) if use_raw
                            else bytes(view)}
            if buf is None:
                return {"missing": True}
        total = buf.size
        end = min(offset + length, total)
        view = buf.view[offset:end]
        # The slice keeps the mmap alive through the transport write;
        # release the store ref NOW so eviction/GC never waits on us.
        buf.release()
        self._m_xfer_out.inc(len(view))
        return {"total_size": total,
                "data": Raw(view) if use_raw else bytes(view)}

    async def object_info(self, object_id: bytes) -> dict:
        """Size/seal state of a local (possibly still-arriving) object.
        Range readers (streaming-shuffle reducers fetching one
        partition's slice of a bundle) call this first to learn the
        object size without pulling a byte of payload."""
        oid = ObjectID(object_id)
        buf = self.store.get_buffer(oid)
        if buf is not None:
            size = buf.size
            buf.release()
            return {"size": size, "sealed": True}
        sink = self._recv_partials.get(object_id)
        if sink is not None:
            return {"size": sink.size, "sealed": sink.sealed}
        return {"missing": True}

    async def stream_pull_object(self, object_id: bytes,
                                 raw: bool = False):
        """Chunked whole-object stream (ref: object_manager.proto Push,
        5 MiB chunks ray_config_def.h:352). Legacy single-source path —
        striped pulls use get_object_chunk; raw=True upgrades the
        payloads to raw frames."""
        oid = ObjectID(object_id)
        use_raw = raw and get_config().transfer_raw_frames
        buf = self.store.get_buffer(oid)
        if buf is None:
            yield {"missing": True}
            return
        try:
            chunk = get_config().object_transfer_chunk_bytes
            total = buf.size
            for off in range(0, total, chunk):
                view = buf.view[off:off + chunk]
                self._m_xfer_out.inc(len(view))
                yield {
                    "offset": off,
                    "total_size": total,
                    "data": Raw(view) if use_raw else bytes(view),
                }
            if total == 0:
                yield {"offset": 0, "total_size": 0, "data": b""}
        finally:
            buf.release()

    async def broadcast_object(self, object_id: bytes,
                               targets: List[str]) -> dict:
        """1->N pre-staging over a log-N relay tree (the weight-
        distribution primitive): this node serves only its <=fanout
        children; each child relays to its subtree WHILE its own copy
        is still arriving (partial re-serve in get_object_chunk). The
        owner's uplink therefore carries fanout*size bytes, not
        N*size. Returns when the whole subtree has sealed."""
        oid = ObjectID(object_id)
        buf = self.store.get_buffer(oid)
        if buf is None:
            return {"ok": False, "error": "object not local"}
        total = buf.size
        buf.release()
        cfg = get_config()
        plan = plan_broadcast_tree(
            [t for t in targets if t != self.server.address],
            cfg.transfer_broadcast_fanout)
        timeout = max(120.0, total / (4 << 20))
        replies = await asyncio.gather(
            *(self._peer_client(child).call(
                "NodeDaemon", "relay_object", object_id=object_id,
                total_size=total, parent_address=self.server.address,
                subtree=subtree, timeout=timeout)
              for child, subtree in plan),
            return_exceptions=True)
        nodes = 0
        errors: List[str] = []
        for rep in replies:
            if isinstance(rep, BaseException):
                errors.append(repr(rep))
            elif rep.get("ok"):
                nodes += rep.get("nodes", 0)
            else:
                errors.append(str(rep.get("error")))
                nodes += rep.get("nodes", 0)
        return {"ok": not errors, "nodes": nodes, "bytes": total,
                "errors": errors}

    async def relay_object(self, object_id: bytes, total_size: int,
                           parent_address: str,
                           subtree: List[str]) -> dict:
        """One node of the broadcast tree: pull chunks from the parent
        (which may itself still be receiving — wait=True long-polls)
        while this node's children pull the same ranges from US as they
        land. The relay returns once this node AND its subtree sealed."""
        oid = ObjectID(object_id)
        cfg = get_config()
        sink: Optional[ChunkSink] = None
        if not self.store.contains(oid):
            sink = self._recv_partials.get(object_id)
            if sink is None:
                try:
                    sink = self._new_recv_sink(object_id, total_size)
                except ObjectExistsError:
                    sink = None      # raced in: serve from the store
        # Children first: they start pulling from this daemon's partial
        # immediately, pipelining the tree instead of serializing it.
        plan = plan_broadcast_tree(
            [t for t in subtree if t != self.server.address],
            cfg.transfer_broadcast_fanout)
        timeout = max(120.0, total_size / (4 << 20))
        child_calls = [
            asyncio.ensure_future(self._peer_client(child).call(
                "NodeDaemon", "relay_object", object_id=object_id,
                total_size=total_size,
                parent_address=self.server.address,
                subtree=st, timeout=timeout))
            for child, st in plan]
        error: Optional[str] = None
        try:
            if sink is not None and not sink.sealed:
                client = self._peer_client(parent_address)
                pending: Dict[asyncio.Task, Tuple[int, int]] = {}
                depth = max(1, cfg.transfer_push_pipeline)
                per_chunk_timeout = cfg.transfer_chunk_timeout_s + 5.0

                def spawn(off: int, ln: int) -> None:
                    task = asyncio.ensure_future(client.call(
                        "NodeDaemon", "get_object_chunk",
                        object_id=object_id, offset=off, length=ln,
                        wait=True, timeout=per_chunk_timeout))
                    pending[task] = (off, ln)

                ranges = chunk_ranges(
                    total_size, cfg.object_transfer_chunk_bytes)
                ranges.reverse()
                try:
                    while (ranges or pending) and not sink.sealed:
                        while ranges and len(pending) < depth:
                            off, ln = ranges.pop()
                            spawn(off, ln)
                        if not pending:
                            break
                        done, _ = await asyncio.wait(
                            pending,
                            return_when=asyncio.FIRST_COMPLETED)
                        for task in done:
                            off, ln = pending.pop(task)
                            rep = task.result()
                            if rep.get("missing"):
                                raise RuntimeError(
                                    f"parent {parent_address} lost "
                                    f"{oid.hex()[:12]} mid-broadcast")
                            sink.write(off, rep["data"])
                            self._m_xfer_in.inc(ln)
                finally:
                    # A racing push may have sealed the sink with our
                    # fetches still out — never leave tasks un-awaited.
                    for task in pending:
                        task.cancel()
                if not sink.sealed:
                    raise RuntimeError("relay pull did not complete")
        except Exception as e:  # noqa: BLE001
            error = repr(e)
            if sink is not None and not sink.sealed:
                self._recv_partials.pop(object_id, None)
                sink.abort()
        child_replies = await asyncio.gather(*child_calls,
                                             return_exceptions=True)
        nodes = 0 if error else 1
        errors = [error] if error else []
        for rep in child_replies:
            if isinstance(rep, BaseException):
                errors.append(repr(rep))
            elif rep.get("ok"):
                nodes += rep.get("nodes", 0)
            else:
                errors.append(str(rep.get("error")))
                nodes += rep.get("nodes", 0)
        if errors:
            return {"ok": False, "nodes": nodes,
                    "error": "; ".join(e for e in errors if e)}
        return {"ok": True, "nodes": nodes}

    def delete_objects(self, object_ids: List[bytes]) -> dict:
        for ob in object_ids:
            self.store.delete(ObjectID(ob), force=False)
        return {"ok": True}

    # ------------------------------------------------------------------
    # stats
    # ------------------------------------------------------------------
    def node_stats(self) -> dict:
        return {
            "node_id": self.node_id,
            "total": self.total,
            "available": self.available,
            "num_workers": len(self._workers),
            "num_idle": len(self._idle),
            "num_leases": len(self._leases),
            "store_used": self.store.used,
            "store_objects": self.store.num_objects,
            "pg_bundles": list(self._pg_bundles.keys()),
        }

    def ping(self) -> dict:
        return {"ok": True}


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--store-dir", default=None)
    parser.add_argument("--object-store-memory", type=int, default=0)
    parser.add_argument("--resources", default="{}")
    args = parser.parse_args()

    logging.basicConfig(
        level=logging.INFO,
        format="[raylet] %(asctime)s %(levelname)s %(message)s")
    # Exit when the spawning driver/launcher dies (workers then follow
    # via their PDEATHSIG, which is safe for THEM: they are forked from
    # this process's long-lived main thread).
    from ray_tpu.core.distributed.driver import start_watch_parent_thread

    start_watch_parent_thread()

    import json

    async def run():
        import signal

        daemon = NodeDaemon(
            gcs_address=args.gcs_address, host=args.host, port=args.port,
            node_id=args.node_id, num_cpus=args.num_cpus,
            num_tpus=args.num_tpus,
            custom_resources=json.loads(args.resources),
            store_dir=args.store_dir,
            object_store_memory=args.object_store_memory)
        port = await daemon.start()
        print(f"DAEMON_PORT={port} NODE_ID={daemon.node_id} "
              f"STORE_DIR={daemon.store_dir}", flush=True)
        stop_event = asyncio.Event()
        loop = asyncio.get_running_loop()
        # Workers fate-share with the daemon (ref: runtime_env
        # ARCHITECTURE.md "fate-shares"): on TERM/INT, kill every child
        # worker before exiting.
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(sig, stop_event.set)
        await stop_event.wait()
        await daemon.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
