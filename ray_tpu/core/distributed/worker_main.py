"""Worker process: executes tasks and hosts at most one actor.

Analogue of the reference worker (ref: python/ray/_private/workers/
default_worker.py bootstrapping a C++ CoreWorker; task execution callback
_raylet.pyx:2251; actor call ordering transport/actor_scheduling_queue.h).
Exposes a `Worker` RPC service the submitters push tasks to directly after a
lease grant (the reference's CoreWorkerService.PushTask,
core_worker.proto:430).
"""
from __future__ import annotations

import argparse
import asyncio
import inspect
import logging
import os
import queue
import threading
from collections import defaultdict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu import exceptions as rexc
from ray_tpu.core import serialization
from ray_tpu.core.config import get_config
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ObjectExistsError
from ray_tpu.core.distributed import protocol
from ray_tpu.core.distributed.core_worker import DistributedCoreWorker
from ray_tpu.core.distributed.rpc import AsyncRpcClient, RpcServer
from ray_tpu.util.profiling import TaskUsageProbe

logger = logging.getLogger(__name__)


class ActorRuntime:
    """Hosts the single actor instance of this worker; enforces per-caller
    submission-order execution (ref: SequentialActorSubmitQueue +
    actor_scheduling_queue.h), with `max_concurrency` pools and async-actor
    event-loop concurrency.

    ANY async method — coroutine or async generator — makes the actor an
    asyncio actor (the reference's rule): default concurrency becomes
    1000 and sync methods lose strict serialization. Keep state-mutating
    methods sync-only in a sync actor, or guard shared state, exactly as
    with the reference's async actors."""

    def __init__(self, instance, max_concurrency: int,
                 concurrency_groups: Optional[Dict[str, int]] = None):
        self.instance = instance
        self._is_async = any(
            inspect.iscoroutinefunction(m)
            or inspect.isasyncgenfunction(m)
            for _, m in inspect.getmembers(type(instance),
                                           inspect.isfunction))
        maxc = max(1, max_concurrency)
        if self._is_async and max_concurrency == 1:
            maxc = 1000
        self.max_concurrency = maxc
        # Named concurrency groups (ref: concurrency_group_manager.h):
        # each group is its own pool, so a blocked "compute" call can
        # never stall "io" calls. Methods pick their group with
        # @ray_tpu.method(concurrency_group=...); undecorated methods
        # run in the default pool. Groups apply to sync methods — async
        # methods keep the shared actor event loop.
        self._groups: Dict[str, ThreadPoolExecutor] = {}
        self._method_groups: Dict[str, str] = {}
        if concurrency_groups:
            for gname, cap in concurrency_groups.items():
                self._groups[gname] = ThreadPoolExecutor(
                    max_workers=max(1, int(cap)),
                    thread_name_prefix=f"cg-{gname}")
        # Scan decorated methods even with NO groups declared: a
        # @method(concurrency_group=...) pointing at an undeclared
        # group must fail loudly, not silently lose its isolation.
        for mname, m in inspect.getmembers(type(instance), callable):
            g = getattr(m, "__ray_tpu_concurrency_group__", None)
            if g is not None:
                if g not in self._groups:
                    raise ValueError(
                        f"method {mname!r} declares concurrency group "
                        f"{g!r} but the actor declares "
                        f"{sorted(self._groups) or 'no groups'} "
                        f"(@remote(concurrency_groups={{...}}))")
                self._method_groups[mname] = g
        # Per-caller ordered batch execution only when ONE serial pool
        # exists: with groups, routing decides the pool per method.
        self._ordered = (maxc == 1 and not self._is_async
                         and not self._groups)
        self._pool = ThreadPoolExecutor(max_workers=maxc)
        self._expected: Dict[str, int] = defaultdict(int)
        self._buffered: Dict[str, Dict[int, Any]] = defaultdict(dict)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        if self._is_async:
            self._loop = asyncio.new_event_loop()
            threading.Thread(target=self._loop.run_forever,
                             daemon=True).start()

    def admit(self, spec: dict, execute) -> "asyncio.Future":
        """Admit in per-caller seq order; the returned future resolves to
        the reply. Plain-future API so a batch RPC admits N specs without
        N coroutine Tasks.

        Ordering key: the caller's per-incarnation order_key (seqs start
        at 0 for every fresh incarnation — the submitter renumbers on
        restart, see core_worker._assign_actor_seq)."""
        caller = spec.get("order_key") or spec["caller_address"]
        seq = spec["seq"]
        main_loop = asyncio.get_running_loop()
        fut: asyncio.Future = main_loop.create_future()
        if seq < self._expected[caller]:
            # Stale-but-valid retry (same incarnation): run immediately
            # rather than orphaning it below the already-advanced base.
            self._dispatch(spec, fut, execute, main_loop)
            return fut
        self._buffered[caller][seq] = (spec, fut)
        self._drain(caller, execute, main_loop)
        return fut

    async def submit(self, spec: dict, execute) -> dict:
        return await self.admit(spec, execute)

    def _drain(self, caller: str, execute, main_loop) -> None:
        buf = self._buffered[caller]
        ready = []
        while self._expected[caller] in buf:
            seq = self._expected[caller]
            ready.append(buf.pop(seq))
            self._expected[caller] += 1
        if not ready:
            return
        if self._ordered and len(ready) > 1:
            # Ordered sync actor (every method sync when _ordered): run
            # the whole contiguous run in ONE pool job — per-call thread
            # dispatch would cost more than the methods themselves. Reply
            # delivery is chunked: one loop wakeup per 64 replies instead
            # of per reply (each call_soon_threadsafe is a syscall + a
            # GIL fight with the executing thread).
            def run_batch():
                chunk = []

                def flush():
                    items, chunk[:] = chunk[:], []

                    def deliver():
                        for f, r in items:
                            if not f.done():
                                f.set_result(r)

                    main_loop.call_soon_threadsafe(deliver)

                for spec, fut in ready:
                    chunk.append((fut, execute(spec)))
                    if len(chunk) >= 64:
                        flush()
                if chunk:
                    flush()

            self._pool.submit(run_batch)
            return
        for spec, fut in ready:
            self._dispatch(spec, fut, execute, main_loop)

    def _dispatch(self, spec, fut, execute, main_loop) -> None:
        method = getattr(self.instance, spec["method_name"], None)
        if (self._loop is not None and method is not None
                and (inspect.iscoroutinefunction(method)
                     or inspect.isasyncgenfunction(method))):
            async def run_async():
                # Arg resolution may block (remote gets): run it on the pool
                # and await via wrap_future (works across loops — the future
                # from another loop's run_in_executor would not).
                reply = await asyncio.wrap_future(
                    self._pool.submit(execute, spec, True))
                if isinstance(reply, dict):       # arg resolution failed
                    main_loop.call_soon_threadsafe(
                        lambda: fut.done() or fut.set_result(reply))
                    return
                args, kwargs = reply
                out = await execute(spec, coro_args=(args, kwargs))
                main_loop.call_soon_threadsafe(
                    lambda: fut.done() or fut.set_result(out))

            asyncio.run_coroutine_threadsafe(run_async(), self._loop)
            return

        def run_sync():
            reply = execute(spec)
            main_loop.call_soon_threadsafe(
                lambda: fut.done() or fut.set_result(reply))

        group = self._method_groups.get(spec["method_name"])
        pool = self._groups[group] if group is not None else self._pool
        pool.submit(run_sync)


# Module-level progress probes: long-running in-process loops (e.g. the
# train session) register a zero-arg callable returning a running-task
# style entry whose `start_ts` is the loop's LAST PROGRESS timestamp.
# `running_tasks` folds these in, so the daemon's hung-task watchdog
# flags a loop that stopped reporting — not one that is merely long.
_progress_probes: Dict[str, Any] = {}
_progress_lock = threading.Lock()


def register_progress_probe(name: str, fn) -> None:
    with _progress_lock:
        _progress_probes[name] = fn


def unregister_progress_probe(name: str) -> None:
    with _progress_lock:
        _progress_probes.pop(name, None)


class WorkerService:
    def __init__(self, core: DistributedCoreWorker, worker_id: str):
        self.core = core
        self.worker_id = worker_id
        self.actor: Optional[ActorRuntime] = None
        self.actor_id: Optional[str] = None
        self._task_pool = ThreadPoolExecutor(max_workers=4,
                                             thread_name_prefix="exec")
        # Async-stream item stores get their OWN thread: offloading to
        # _task_pool could circular-wait (a pooled task blocked on a
        # stream item whose store needs a pool slot).
        self._stream_store_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="stream-store")
        self._max_inline = get_config().max_inline_object_size
        # task_id -> executing thread ident, for cooperative
        # cancellation of RUNNING tasks (ref: CancelTask interrupting
        # the worker): cancel_task injects KeyboardInterrupt into the
        # thread at the next bytecode boundary.
        self._executing: Dict[bytes, int] = {}
        # max_calls retirement (ref: worker lifetime bounded per
        # executed-invocation count OF THAT FUNCTION — bounds leaks
        # from user/native code without churning mixed workloads).
        self._exec_counts: Dict[bytes, int] = {}
        self._retire_after_reply = False
        # Insertion-ordered (dict) so bounding evicts the OLDEST
        # tombstones, never a cancel that just arrived.
        self._cancelled_here: Dict[bytes, None] = {}
        # Makes interrupt injection atomic with execution membership:
        # cancel_task injects ONLY while the target is registered, and
        # deregistration (finally) takes the same lock — so a pending
        # KeyboardInterrupt always lands inside _execute's try, never
        # escaping into the pool's worker loop (which would kill the
        # pool thread permanently).
        self._exec_lock = threading.Lock()
        # Task-event pipeline (task_events.py TaskEventBuffer on the
        # core, ref: gcs_task_manager.h — powers `ray-tpu list tasks`
        # and the chrome-trace timeline): bounded ring + coalescing
        # flusher, drops counted instead of silent.
        self.core.task_events.worker_id = worker_id
        # Per-task resource attribution (profiling.TaskUsageProbe):
        # thread CPU-time + RSS delta/peak per attempt, riding the
        # attempt's task-event record. Resolved once — workers get the
        # knob through their spawn env.
        self._attrib = get_config().task_events_resources
        # task_id -> live attempt info for the daemon's hung-task
        # watchdog (`running_tasks` RPC). Plain dict, GIL-atomic
        # set/pop of whole entries; readers snapshot with list().
        self._running_info: Dict[bytes, dict] = {}
        # Pre-leased task lanes pinned to this worker: lane_id -> the
        # spec template the per-call delta frames are expanded against
        # (fn_key/name/job_id travel ONCE at lane_open, never per call).
        self._lanes: Dict[str, dict] = {}
        # Compiled-DAG stage loops (lane_apply) get their own threads:
        # they run for the DAG's lifetime, and parking one in
        # _task_pool would wedge the retirement drain.
        self._lane_pool: Optional[ThreadPoolExecutor] = None

    def _record_event(self, spec: dict, state: str, start_ts: float,
                      end_ts: float, error: Optional[str] = None,
                      usage: Optional[dict] = None) -> None:
        """Record an attempt's FULL history in one coalesced record: the
        submission half (SUBMITTED/LEASED timestamps + caller identity)
        rides the spec itself, so the happy path ships a single wire
        record per attempt instead of two GCS-merged halves. `usage` is
        the attempt's resource attribution (TaskUsageProbe.finish())."""
        transitions = []
        sub_ts = spec.get("submit_ts")
        ctx = spec.get("submit_ctx") or (None, None)
        if sub_ts is not None:
            transitions.append(("SUBMITTED", sub_ts))
        lease_ts = spec.get("lease_ts")
        if lease_ts is not None:
            transitions.append(("LEASED", lease_ts))
        transitions.append(("RUNNING", start_ts))
        transitions.append((state, end_ts))
        self.core.task_events.record_attempt(
            spec["task_id"].hex(), spec.get("attempt", 0), transitions,
            error=error, name=spec["options"].get("name", "task"),
            job_id=spec.get("job_id"), actor_id=spec.get("actor_id"),
            worker_id=self.worker_id, pid=os.getpid(),
            submit_node_id=ctx[0], submit_pid=ctx[1], **(usage or {}))

    # ---- helpers ------------------------------------------------------
    def _fetch_arg(self, oid: ObjectID,
                   owner: Optional[str] = None) -> Any:
        from ray_tpu.core.distributed.pull_manager import PRIORITY_TASK_ARG

        # The owner address (from the RefMarker) routes small values to
        # the owner's inline cache when the store/directory has no copy.
        return self.core.get([_mkref(oid, owner)], timeout=300,
                             _priority=PRIORITY_TASK_ARG)[0]

    def _store_results(self, spec: dict, value: Any,
                       is_error: bool = False) -> List[protocol.TaskResult]:
        num_returns = spec["num_returns"]
        task_id_b = spec["task_id"]
        out: List[protocol.TaskResult] = []
        if is_error:
            values = [value] * num_returns
        elif num_returns == 1:
            values = [value]
        elif isinstance(value, (tuple, list)) and len(value) == num_returns:
            values = list(value)
        else:
            err = rexc.TaskError(
                spec["options"].get("name", "task"),
                f"declared num_returns={num_returns} but returned "
                f"{type(value).__name__}")
            return self._store_results(spec, err, is_error=True)
        from ray_tpu.core.ids import TaskID

        task_id = TaskID(task_id_b)
        for i, v in enumerate(values):
            oid = ObjectID.for_task_return(task_id, i + 1)
            if v is None and not is_error:
                # The most common return on control-flow hot paths
                # (noop tasks, side-effect actors): one cached payload.
                payload = _none_payload()
                meta = bufs = None
                size = len(payload)
            else:
                # Serialize to (header, out-of-band buffers) and only
                # materialize a contiguous payload when it fits inline;
                # large results land in the store mmap via put_serialized
                # — one copy, no BytesIO round-trip.
                meta, bufs = serialization.serialize(v, is_error=is_error)
                size = serialization.serialized_size(meta, bufs)
                payload = (serialization.concat(meta, bufs)
                           if size <= self._max_inline else None)
            inline = payload if size <= self._max_inline else None
            if inline is not None:
                # The caller consumes the inline copy from the reply and
                # becomes the object's authoritative copy: third-party
                # readers fetch from the OWNER (OwnerService), so the
                # happy path makes no store write or directory record
                # (ref: owner-based in-process memory store,
                # core_worker.cc HandleGetObjectStatus). RETRIED tasks
                # write through: if this attempt's reply is lost too,
                # the next retry converges via _existing_results
                # instead of re-running the body again.
                if spec.get("attempt", 0) or spec.get("_lane_retries"):
                    try:
                        self.core.store.put_raw(oid, payload)
                    except ObjectExistsError:
                        pass
                    except Exception:  # noqa: BLE001 store full
                        pass
                    else:
                        self.core.queue_location(oid, size)
            else:
                # No inline copy: the store write must land before the
                # reply or the caller's get() would race a missing object.
                try:
                    if payload is not None:
                        self.core.store.put_raw(oid, payload)
                    else:
                        self.core.store.put_serialized(oid, meta, bufs)
                except ObjectExistsError:
                    # Retried task, contents identical; still re-register —
                    # the first attempt may have died before add_location.
                    pass
                self.core.queue_location(oid, size)
            out.append(protocol.TaskResult(oid=oid.binary(),
                                           size=size,
                                           inline=inline,
                                           is_error=is_error))
        return out

    def _stream_reply(self, spec: dict, result: Any, start_ts: float,
                      error_cls=None, probe=None) -> dict:
        """Run the streaming body + record the task event (shared by
        the task and actor execution paths)."""
        import time as _time

        reply = self._execute_stream(spec, result, error_cls=error_cls)
        self._record_event(
            spec, "FAILED" if reply["error"] else "FINISHED",
            start_ts, _time.time(),
            error=repr(reply["error"]) if reply["error"] else None,
            usage=probe.finish() if probe is not None else None)
        return reply

    def _execute_stream(self, spec: dict, result: Any,
                        error_cls=None) -> dict:
        """Streaming task body: each yield is stored + its location
        registered IMMEDIATELY (consumers discover in-flight items
        through the directory, core/streaming.py); the reply carries
        the full item list (with inline copies of small values) so the
        owner can fix the final count and serve completed-stream gets
        locally."""
        from ray_tpu.core.ids import TaskID

        error_cls = error_cls or rexc.TaskError
        name = spec["options"].get("name", "task")
        if not inspect.isgenerator(result):
            return {"results": [], "error": error_cls(
                name, f"num_returns='streaming' task returned "
                      f"{type(result).__name__}, not a generator")}
        task_id = TaskID(spec["task_id"])
        results: List[protocol.TaskResult] = []
        error = None
        try:
            # Register for cancel-interrupt injection around the
            # ITERATION (the generator body runs here, not at fn()-call
            # time in _execute, whose registration window closed before
            # the first yield executed). The tombstone check happens
            # ATOMICALLY with registration: a cancel that landed in the
            # unregistered gap left only the tombstone (no thread to
            # interrupt) — honoring it without registering means
            # cancel_task can never ALSO inject (it only injects at
            # registered tasks, under this same lock), so no stray
            # second interrupt escapes to a later task.
            precancelled = False
            with self._exec_lock:
                if spec["task_id"] in self._cancelled_here:
                    precancelled = True
                else:
                    self._executing[spec["task_id"]] = \
                        threading.get_ident()
            try:
                if precancelled:
                    raise KeyboardInterrupt  # handler consumes tombstone
                for i, v in enumerate(result, start=1):
                    results.append(self._store_stream_item(task_id, i, v))
            finally:
                with self._exec_lock:
                    self._executing.pop(spec["task_id"], None)
        except BaseException as e:  # noqa: BLE001
            # Same stray-interrupt discipline as _execute: deregister
            # again (idempotent — injection can land mid-finally).
            with self._exec_lock:
                self._executing.pop(spec["task_id"], None)
            if isinstance(e, KeyboardInterrupt):
                if spec["task_id"] in self._cancelled_here:
                    self._cancelled_here.pop(spec["task_id"], None)
                    error = rexc.TaskCancelledError(name)
                else:
                    error = rexc.WorkerCrashedError(
                        f"stream {name} interrupted by a stray cancel")
            else:
                error = (e if isinstance(e, rexc.RayTpuError)
                         else error_cls.from_exception(
                             e, name, pid=os.getpid(),
                             node_id=self.core.node_id))
        return {"results": results, "error": error}

    def _store_stream_item(self, task_id, i: int,
                           v: Any) -> protocol.TaskResult:
        """Store + register one stream yield so consumers discover it
        immediately (shared by the sync and async-generator paths)."""
        oid = ObjectID.for_task_return(task_id, i)
        meta, bufs = serialization.serialize(v)
        size = serialization.serialized_size(meta, bufs)
        inline = (serialization.concat(meta, bufs)
                  if size <= self._max_inline else None)
        try:
            if inline is not None:
                self.core.store.put_raw(oid, inline)
            else:
                # Large stream items: one copy straight into the store
                # mmap (no contiguous dumps() intermediate).
                self.core.store.put_serialized(oid, meta, bufs)
        except ObjectExistsError:
            pass   # retried stream: identical contents
        self.core.queue_location(oid, size)
        return protocol.TaskResult(oid=oid.binary(), size=size,
                                   inline=inline, is_error=False)

    async def _execute_stream_async(self, spec: dict, agen,
                                    start_ts: float, name: str) -> dict:
        """Async-generator actor methods: same per-item storage, driven
        by `async for`. Serialization + store writes are offloaded to
        the task pool — the actor's event loop (shared by every
        in-flight coroutine method) must not block on store I/O."""
        import time as _time

        from ray_tpu.core.ids import TaskID

        loop = asyncio.get_running_loop()
        task_id = TaskID(spec["task_id"])
        results: List[protocol.TaskResult] = []
        error = None
        try:
            i = 0
            async for v in agen:
                i += 1
                results.append(await loop.run_in_executor(
                    self._stream_store_pool, self._store_stream_item,
                    task_id, i, v))
        except BaseException as e:  # noqa: BLE001
            # Close promptly: the user generator's finally blocks must
            # not wait for the loop's asyncgen GC finalizer.
            try:
                await agen.aclose()
            except BaseException:  # noqa: BLE001
                pass
            error = (e if isinstance(e, rexc.RayTpuError)
                     else rexc.ActorError.from_exception(
                         e, name, pid=os.getpid(),
                         node_id=self.core.node_id))
        self._record_event(
            spec, "FAILED" if error else "FINISHED", start_ts,
            _time.time(), error=repr(error) if error else None)
        return {"results": results, "error": error}

    def _existing_results(self, spec: dict) -> Optional[List[
            protocol.TaskResult]]:
        """Retry memoization: if a prior attempt already stored every
        return of this task in the node's store (the attempt's reply died
        with its RPC, not its results), reuse them instead of re-running
        the function — retried batches converge instead of repeating
        completed work (return ObjectIDs are attempt-independent)."""
        from ray_tpu.core.ids import TaskID

        task_id = TaskID(spec["task_id"])
        out: List[protocol.TaskResult] = []
        for i in range(spec["num_returns"]):
            oid = ObjectID.for_task_return(task_id, i + 1)
            buf = self.core.store.get_buffer(oid)
            if buf is None:
                return None
            try:
                payload = bytes(buf.view)
            finally:
                buf.release()
            is_err = serialization.is_error_payload(payload)
            inline = (payload if len(payload) <= self._max_inline
                      else None)
            if is_err and inline is None:
                return None  # can't rebuild the error reply; re-execute
            self.core.queue_location(oid, len(payload))
            out.append(protocol.TaskResult(
                oid=oid.binary(), size=len(payload), inline=inline,
                is_error=is_err))
        return out

    def _running_entry(self, spec: dict, name: str) -> dict:
        import time as _time

        actor_id = spec.get("actor_id")
        return {
            "task_id": spec["task_id"].hex(),
            "attempt": spec.get("attempt", 0),
            "name": name,
            "job_id": spec.get("job_id"),
            "actor_id": (actor_id.hex() if isinstance(actor_id, bytes)
                         else actor_id),
            "start_ts": _time.time(),
        }

    def _execute(self, spec: dict) -> dict:
        """Tracked execution: the attempt is visible to the daemon's
        hung-task watchdog (`running_tasks`) for exactly as long as it
        occupies an executor thread."""
        key = spec["task_id"]
        self._running_info[key] = self._running_entry(
            spec, spec["options"].get("name", "task"))
        try:
            return self._execute_task(spec)
        finally:
            self._running_info.pop(key, None)

    def _execute_task(self, spec: dict) -> dict:
        name = spec["options"].get("name", "task")
        if (spec.get("attempt", 0) or spec.get("_lane_retries")) \
                and not spec["options"].get("streaming"):
            # (streaming: num_returns==0 would make the empty prior list
            # read as a memoized success; restarts are idempotent anyway
            # — item ObjectIDs are attempt-independent.)
            prior = self._existing_results(spec)
            if prior is not None:
                err = None
                if prior and prior[0].is_error:
                    try:
                        serialization.deserialize(prior[0].inline)
                    except BaseException as e:  # noqa: BLE001 the payload
                        err = e
                return {"results": prior, "error": err}
        import time as _time

        start_ts = _time.time()
        if spec["task_id"] in self._cancelled_here:
            # Cancelled while queued in an in-flight batch on THIS
            # worker: never execute (and never charge max_calls budget).
            self._cancelled_here.pop(spec["task_id"], None)
            err = rexc.TaskCancelledError(name)
            self._record_event(spec, "FAILED", start_ts, _time.time(),
                               error=repr(err))
            return {"results": [], "error": err}
        if self._retire_after_reply:
            # Budget exhausted: hand the spec back to the lane (the
            # `requeue` sentinel re-queues WITHOUT charging the task's
            # retry budget — the task never executed).
            return {"requeue": True, "results": [], "error": None}
        mc = spec["options"].get("max_calls") or 0
        if mc:
            # Under _exec_lock: up to 4 pool threads race this RMW, and a
            # lost increment would let the worker exceed its budget.
            with self._exec_lock:
                n = self._exec_counts.get(spec["fn_key"], 0) + 1
                self._exec_counts[spec["fn_key"]] = n
                if n >= mc:
                    self._retire_after_reply = True
        # RUNNING is visible mid-execution (long tasks show up in
        # list_tasks before they finish), not only in the terminal
        # record's back-dated history. Lean on purpose: the buffer
        # stamps executor identity, the terminal record fills the rest.
        self.core.task_events.record_status(
            spec["task_id"].hex(), spec.get("attempt", 0), "RUNNING",
            ts=start_ts, name=name, job_id=spec.get("job_id"))
        probe = TaskUsageProbe() if self._attrib else None
        try:
            fn = self.core.fetch_function(spec["fn_key"])
            args, kwargs = protocol.unpack_args(spec["args_blob"],
                                                self._fetch_arg)
            from ray_tpu.util import tracing

            with tracing.extract_and_span(spec.get("trace_ctx"),
                                          f"task:{name}",
                                          task_id=spec["task_id"].hex()):
                with self._exec_lock:
                    self._executing[spec["task_id"]] = \
                        threading.get_ident()
                try:
                    result = fn(*args, **kwargs)
                    if inspect.iscoroutine(result):
                        result = asyncio.run(result)
                finally:
                    with self._exec_lock:
                        self._executing.pop(spec["task_id"], None)
                if spec["options"].get("streaming"):
                    return self._stream_reply(spec, result, start_ts,
                                              probe=probe)
            reply = {"results": self._store_results(spec, result),
                     "error": None}
            self._record_event(spec, "FINISHED", start_ts, _time.time(),
                               usage=probe.finish() if probe else None)
            return reply
        except BaseException as e:  # noqa: BLE001
            # An injected interrupt can land BEFORE the inner try or
            # WHILE its finally acquires the lock, skipping the pop —
            # deregister again (idempotent) so no stale entry can route
            # a later injection at an innocent task.
            with self._exec_lock:
                self._executing.pop(spec["task_id"], None)
            if isinstance(e, KeyboardInterrupt):
                if spec["task_id"] in self._cancelled_here:
                    self._cancelled_here.pop(spec["task_id"], None)
                    err = rexc.TaskCancelledError(name)
                else:
                    # An injected interrupt that landed AFTER its
                    # target finished hit this unrelated task: surface
                    # as a retryable system failure, not an app error.
                    err = rexc.WorkerCrashedError(
                        f"task {name} interrupted by a stray cancel")
            elif isinstance(e, rexc.RayTpuError):
                err = e
            else:
                err = rexc.TaskError.from_exception(
                    e, name, pid=os.getpid(),
                    node_id=self.core.node_id)
            try:
                self._store_results(spec, err, is_error=True)
            except Exception:  # noqa: BLE001
                pass
            self._record_event(spec, "FAILED", start_ts, _time.time(),
                               error=repr(e),
                               usage=probe.finish() if probe else None)
            return {"results": [], "error": err}

    # ---- RPC surface --------------------------------------------------
    def _maybe_retire(self) -> None:
        """Exit (after the reply flushes) once a task whose max_calls
        budget this worker exhausted has completed; the daemon's pool
        respawns and lease holders ride the ordinary worker-death retry
        path."""
        if not self._retire_after_reply:
            return
        if getattr(self, "_retiring", False):
            return
        self._retiring = True
        logger.info("worker retiring (max_calls reached)")

        def die():
            import time as _time

            # Drain first: a task still executing in another pool slot
            # must finish before exit, or its side effects run twice —
            # the lane's connection-failure requeue does NOT charge
            # max_retries (the reference drains the worker before exit).
            # Pool shutdown (not an _executing poll) so a spec still
            # fetching args counts too; specs that reach _execute after
            # the retire flag get the `requeue` sentinel and finish
            # instantly. Join is bounded: a never-ending task shouldn't
            # pin the worker slot forever.
            waiter = threading.Thread(
                target=lambda: self._task_pool.shutdown(wait=True),
                daemon=True)
            waiter.start()
            waiter.join(60.0)
            # Then long enough for the (local-socket) reply bytes to
            # flush; refused specs are requeued by the lane with a delay
            # spanning this window, so they re-lease a fresh worker.
            _time.sleep(0.2)
            os._exit(0)

        threading.Thread(target=die, daemon=True).start()

    async def cancel_task(self, task_id: bytes) -> dict:
        """Interrupt a RUNNING task (ref: CancelTask): injects
        KeyboardInterrupt into the executing thread, which lands at the
        next Python bytecode boundary (a task blocked in a C call —
        time.sleep, a jitted step — is interrupted when it returns).
        Best-effort by design."""
        self._cancelled_here[task_id] = None
        # Bound the tombstones: a cancel that misses (task already
        # finished) would otherwise leak its entry forever. Oldest-first
        # eviction cannot drop the entry just added.
        while len(self._cancelled_here) > 4096:
            del self._cancelled_here[next(iter(self._cancelled_here))]
        import ctypes

        with self._exec_lock:
            tid = self._executing.get(task_id)
            if tid is None:
                return {"interrupted": False}
            n = ctypes.pythonapi.PyThreadState_SetAsyncExc(
                ctypes.c_ulong(tid), ctypes.py_object(KeyboardInterrupt))
            if n > 1:   # should not happen; undo rather than spray
                ctypes.pythonapi.PyThreadState_SetAsyncExc(
                    ctypes.c_ulong(tid), None)
        return {"interrupted": n == 1}

    async def push_task(self, spec: dict) -> dict:
        loop = asyncio.get_running_loop()
        try:
            reply = await loop.run_in_executor(self._task_pool,
                                               self._execute, spec)
        except RuntimeError:
            # Pool shut down by the retirement drain while this push was
            # in flight: the spec never ran — requeue, don't charge
            # retries (without this, a max_retries=0 task arriving in
            # the drain window would fail permanently unexecuted).
            return {"requeue": True, "results": [], "error": None}
        self._maybe_retire()
        return reply

    async def push_tasks_stream(self, specs: List[dict]):
        """Batched task push from a lease-reuse lane, with STREAMED
        `(index, reply)` items. The batch executes SEQUENTIALLY in one
        pool slot — the whole batch rides a single lease, so running
        specs in parallel would oversubscribe the resources that lease
        reserved (parallelism comes from the lane holding multiple
        leases, each its own batch) — but each task's reply leaves the
        worker as soon as IT finishes, so a fast task's caller — a
        get()/wait() at the owner — is never gated on a slow
        batchmate. With owner-served small results the reply IS result
        visibility, which is why per-task delivery matters (ref: the
        reference pushes tasks individually and gets this for free)."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def run_all():
            # The end sentinel is UNCONDITIONAL: an exception escaping
            # _execute (stray injected interrupt between tasks, store
            # failure in a pre-try region) must not strand the stream —
            # the lane would wait forever on a batch that never ends.
            try:
                for i, s in enumerate(specs):
                    reply = self._execute(s)
                    loop.call_soon_threadsafe(q.put_nowait, (i, reply))
            except BaseException as e:  # noqa: BLE001
                logger.exception("batch executor died mid-stream")
                raise e
            finally:
                try:
                    loop.call_soon_threadsafe(q.put_nowait, None)
                except RuntimeError:
                    pass   # loop closing; the connection dies with it

        try:
            pool_fut = loop.run_in_executor(self._task_pool, run_all)
        except RuntimeError:
            # Retirement drain closed the pool mid-push: see push_task.
            yield [(i, {"requeue": True, "results": [], "error": None})
                   for i in range(len(specs))]
            return
        try:
            done = False
            while not done:
                item = await q.get()
                if item is None:
                    break
                # Coalesce everything already completed into ONE frame:
                # micro-tasks that outpace the socket amortize framing
                # like the old batched reply did, while a slow task's
                # reply still leaves the moment it finishes.
                chunk = [item]
                while True:
                    try:
                        nxt = q.get_nowait()
                    except asyncio.QueueEmpty:
                        break
                    if nxt is None:
                        done = True
                        break
                    chunk.append(nxt)
                yield chunk
            await pool_fut
        finally:
            # A client disconnect/cancel closes this generator at a
            # yield: still consume the executor future's exception (no
            # 'never retrieved' noise) and run the retirement check the
            # tail would otherwise have done.
            def _consume(f):
                try:
                    f.exception()
                except Exception:  # noqa: BLE001
                    pass

            pool_fut.add_done_callback(_consume)
            self._maybe_retire()

    # ---- pre-leased task lanes (compiled execution plane) -------------
    async def lane_open(self, lane_id: str, fn_key: bytes,
                        name: str = "task",
                        job_id: Optional[str] = None,
                        submit_ctx=None) -> dict:
        """Open a lane on this (pinned) worker: prefetch the function and
        record the spec template, so each subsequent `lane_execute` delta
        frame carries only (task id, arg blob, counters) — no TaskSpec
        pickle, no function-table lookup on the hot path."""
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(self._task_pool,
                                       self.core.fetch_function, fn_key)
        except RuntimeError:
            return {"requeue": True, "ok": False}   # retiring; re-lease
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": str(e)}
        self._lanes[lane_id] = {"fn_key": fn_key, "name": name,
                                "job_id": job_id,
                                "submit_ctx": submit_ctx}
        return {"ok": True}

    async def lane_execute(self, lane_id: str, task_id: bytes,
                           args_blob, num_returns: int = 1,
                           attempt: int = 0,
                           lane_retries: int = 0,
                           submit_ts: Optional[float] = None,
                           lease_ts: Optional[float] = None) -> dict:
        """One lane call: expand the delta frame against the lane's spec
        template and run it through the ordinary tracked executor (same
        memoization, cancellation, retirement and result-storing
        semantics as push_task)."""
        lane = self._lanes.get(lane_id)
        if lane is None:
            # Lane evaporated (worker restarted under the same address,
            # or close raced a call): hand the call back untouched.
            return {"requeue": True, "results": [], "error": None}
        spec = {
            "task_id": task_id,
            "fn_key": lane["fn_key"],
            "args_blob": args_blob,
            "num_returns": num_returns,
            "options": {"name": lane["name"]},
            "attempt": attempt,
            "_lane_retries": lane_retries,
            "job_id": lane["job_id"],
            # Submission history rides the delta frame (two floats), so
            # laned attempts report the same SUBMITTED→LEASED→RUNNING→
            # terminal transitions as fully-specced ones.
            "submit_ts": submit_ts,
            "lease_ts": lease_ts,
            "submit_ctx": lane["submit_ctx"],
        }
        loop = asyncio.get_running_loop()
        try:
            reply = await loop.run_in_executor(self._task_pool,
                                               self._execute, spec)
        except RuntimeError:
            # Retirement drain closed the pool mid-call: never executed.
            return {"requeue": True, "results": [], "error": None}
        self._maybe_retire()
        return reply

    async def lane_apply(self, blob, name: str = "dag_stage") -> dict:
        """Run a long-lived body (a compiled-DAG FunctionNode stage loop)
        in this pinned worker: `blob` is a cloudpickled zero-arg
        callable; the call returns when the loop exits (channel close at
        teardown). The RPC reply doubles as the loop ref."""
        loop = asyncio.get_running_loop()
        if self._lane_pool is None:
            self._lane_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="lane")

        def run():
            fn = serialization.cloudpickle.loads(blob)
            return fn()

        try:
            await loop.run_in_executor(self._lane_pool, run)
            return {"error": None}
        except BaseException as e:  # noqa: BLE001
            if isinstance(e, rexc.RayTpuError):
                err = e
            else:
                err = rexc.TaskError.from_exception(
                    e, name, pid=os.getpid(), node_id=self.core.node_id)
            return {"error": err}

    async def lane_close(self, lane_id: str) -> dict:
        self._lanes.pop(lane_id, None)
        return {"ok": True}

    async def create_actor(self, actor_id: str, cls_blob_key: bytes,
                           args_blob: bytes,
                           max_concurrency: int = 1,
                           concurrency_groups: Optional[
                               Dict[str, int]] = None) -> dict:
        loop = asyncio.get_running_loop()

        def construct():
            cls = self.core.fetch_function(cls_blob_key)
            args, kwargs = protocol.unpack_args(args_blob, self._fetch_arg)
            return cls(*args, **kwargs)

        try:
            instance = await loop.run_in_executor(self._task_pool, construct)
        except BaseException as e:  # noqa: BLE001
            logger.exception("actor construction failed")
            return {"ok": False, "error": repr(e)}
        # Generic escape hatch used by compiled DAGs (the reference's
        # `__ray_call__`, actor.py): run an arbitrary function with the
        # actor instance as first argument, on the actor's own thread.
        def __raytpu_apply__(fn, *a, **kw):
            return fn(instance, *a, **kw)

        try:
            instance.__raytpu_apply__ = __raytpu_apply__
        except AttributeError:
            pass  # __slots__ class: compiled DAG loops unsupported on it
        try:
            self.actor = ActorRuntime(instance, max_concurrency,
                                      concurrency_groups)
        except Exception as e:  # noqa: BLE001 bad group declaration:
            # surface as a creation failure, not a hung actor.
            logger.exception("actor runtime setup failed")
            return {"ok": False, "error": repr(e)}
        self.actor_id = actor_id
        return {"ok": True}

    async def push_actor_task(self, spec: dict) -> dict:
        if self.actor is None:
            return {"results": [],
                    "error": rexc.ActorDiedError(spec.get("actor_id") or "",
                                                 "no actor on this worker")}
        return await self.actor.submit(spec, self._execute_actor)

    async def push_actor_tasks(self, specs: List[dict]) -> List[dict]:
        """Batched push (one RPC per caller-side burst): admission stays
        per-spec (seq ordering), execution of a contiguous ordered run is
        drained in a single pool job."""
        if self.actor is None:
            err = rexc.ActorDiedError(
                (specs[0].get("actor_id") if specs else "") or "",
                "no actor on this worker")
            return [{"results": [], "error": err} for _ in specs]
        # Plain sequential awaits, not gather(): admit() returns real
        # futures, the batch completes roughly in order, and gather's
        # per-child callback wiring is measurable at 10k+ calls/s.
        replies = list(await asyncio.gather(*[
            self.actor.admit(s, self._execute_actor) for s in specs]))
        # Wire-compress the dominant reply shape — a single inline None
        # return (side-effect actor methods) — to the integer 0. The
        # IDENTITY check against the cached none payload is exact: only
        # _store_results' None fast path produces that object, always as
        # the sole return of a num_returns=1 call, so the caller can
        # reconstruct the full TaskResult from its own return_ids (see
        # core_worker._finish_actor_batch).
        np = _none_payload()
        for i, r in enumerate(replies):
            if r.get("error") is None:
                res = r["results"]
                if len(res) == 1 and res[0].inline is np:
                    replies[i] = 0
        return replies

    async def push_actor_tasks_delta(self, template: dict,
                                     deltas: List[tuple]) -> List[dict]:
        """Delta-frame push: a same-destination burst arrives as ONE
        template spec plus per-call (task_id, seq, submit_ts) tuples
        (see core_worker._delta_frame). Reconstitute full specs and run
        the ordinary batched admission path."""
        specs = []
        for task_id, seq, submit_ts in deltas:
            s = dict(template)
            s["task_id"] = task_id
            s["seq"] = seq
            s["submit_ts"] = submit_ts
            specs.append(s)
        return await self.push_actor_tasks(specs)

    def _execute_actor(self, spec: dict, resolve_only: bool = False,
                       coro_args=None):
        """Tracked actor execution (see _execute): arg-resolution passes
        are not tracked — only phases that can actually hang user-visibly
        on this method's body."""
        if resolve_only:
            return self._execute_actor_impl(spec, resolve_only, coro_args)
        key = spec["task_id"]
        name = (f"{type(self.actor.instance).__name__}."
                f"{spec['method_name']}" if self.actor is not None
                else spec["method_name"])
        entry = self._running_entry(spec, name)
        if coro_args is not None:
            inner = self._execute_actor_impl(spec, resolve_only, coro_args,
                                             name=name)

            async def tracked():
                self._running_info[key] = entry
                try:
                    return await inner
                finally:
                    self._running_info.pop(key, None)

            return tracked()
        self._running_info[key] = entry
        try:
            return self._execute_actor_impl(spec, resolve_only, coro_args,
                                            name=name)
        finally:
            self._running_info.pop(key, None)

    def _execute_actor_impl(self, spec: dict, resolve_only: bool = False,
                            coro_args=None, name: Optional[str] = None):
        if name is None:
            name = (f"{type(self.actor.instance).__name__}."
                    f"{spec['method_name']}")
        import time as _time

        if coro_args is not None:
            # Async path phase 2: returns an awaitable producing the reply.
            async def run():
                start_ts = _time.time()
                if spec["task_id"] in self._cancelled_here:
                    # Cancelled while buffered: reply (keeping seq
                    # contiguity) without invoking the method.
                    self._cancelled_here.pop(spec["task_id"], None)
                    err = rexc.TaskCancelledError(name)
                    self._record_event(spec, "FAILED", start_ts,
                                       _time.time(), error=repr(err))
                    return {"results": [], "error": err}
                try:
                    method = getattr(self.actor.instance,
                                     spec["method_name"])
                    if spec["options"].get("streaming"):
                        if not inspect.isasyncgenfunction(method):
                            # Reject BEFORE invoking: calling a plain
                            # coroutine method would create a never-
                            # awaited coroutine and silently skip its
                            # side effects. (Sync generator methods on
                            # async actors never reach this path —
                            # _dispatch routes them to the sync pool.)
                            err = rexc.ActorError(
                                name, "num_returns='streaming' async "
                                      "actor method must be an async "
                                      "generator (async def + yield)")
                            self._record_event(
                                spec, "FAILED", start_ts, _time.time(),
                                error=repr(err))
                            return {"results": [], "error": err}
                        raw = method(*coro_args[0], **coro_args[1])
                        return await self._execute_stream_async(
                            spec, raw, start_ts, name)
                    if inspect.isasyncgenfunction(method):
                        # awaiting an async generator is a TypeError —
                        # diagnose the missing option instead.
                        err = rexc.ActorError(
                            name, "async-generator method requires "
                                  "num_returns='streaming'")
                        self._record_event(
                            spec, "FAILED", start_ts, _time.time(),
                            error=repr(err))
                        return {"results": [], "error": err}
                    result = await method(*coro_args[0], **coro_args[1])
                    reply = {"results": self._store_results(spec, result),
                             "error": None}
                    self._record_event(spec, "FINISHED", start_ts,
                                       _time.time())
                    return reply
                except BaseException as e:  # noqa: BLE001
                    err = rexc.ActorError.from_exception(
                        e, name, pid=os.getpid(), node_id=self.core.node_id)
                    self._store_results(spec, err, is_error=True)
                    self._record_event(spec, "FAILED", start_ts,
                                       _time.time(), error=repr(e))
                    return {"results": [], "error": err}

            return run()
        try:
            args, kwargs = protocol.unpack_args(spec["args_blob"],
                                                self._fetch_arg)
        except BaseException as e:  # noqa: BLE001
            err = rexc.TaskError.from_exception(e, name)
            return {"results": [], "error": err}
        if resolve_only:
            return args, kwargs
        start_ts = _time.time()
        if spec["task_id"] in self._cancelled_here:
            # Cancelled while queued in the actor's ordered buffer: the
            # reply keeps seq contiguity, the method never runs.
            self._cancelled_here.pop(spec["task_id"], None)
            err = rexc.TaskCancelledError(name)
            self._record_event(spec, "FAILED", start_ts, _time.time(),
                               error=repr(err))
            return {"results": [], "error": err}
        probe = TaskUsageProbe() if self._attrib else None
        try:
            method = getattr(self.actor.instance, spec["method_name"])
            trace_ctx = spec.get("trace_ctx")
            if trace_ctx is None:
                # Hot path: no submitted trace context means no span can
                # open (extract_and_span yields None) — skip the span-arg
                # construction and generator/contextmanager machinery.
                span_cm = _NULL_SPAN
            else:
                from ray_tpu.util import tracing

                span_cm = tracing.extract_and_span(
                    trace_ctx, f"actor:{name}",
                    task_id=spec["task_id"].hex())
            with span_cm:
                with self._exec_lock:
                    self._executing[spec["task_id"]] = \
                        threading.get_ident()
                try:
                    result = method(*args, **kwargs)
                    if inspect.iscoroutine(result):
                        result = asyncio.run(result)
                finally:
                    with self._exec_lock:
                        self._executing.pop(spec["task_id"], None)
                if spec["options"].get("streaming"):
                    return self._stream_reply(spec, result, start_ts,
                                              error_cls=rexc.ActorError,
                                              probe=probe)
            reply = {"results": self._store_results(spec, result),
                     "error": None}
            self._record_event(spec, "FINISHED", start_ts, _time.time(),
                               usage=probe.finish() if probe else None)
            return reply
        except BaseException as e:  # noqa: BLE001
            with self._exec_lock:
                self._executing.pop(spec["task_id"], None)
            if isinstance(e, KeyboardInterrupt):
                if spec["task_id"] in self._cancelled_here:
                    self._cancelled_here.pop(spec["task_id"], None)
                    err = rexc.TaskCancelledError(name)
                else:
                    err = rexc.WorkerCrashedError(
                        f"actor method {name} interrupted by a stray "
                        f"cancel")
            elif isinstance(e, rexc.RayTpuError):
                # Typed passthrough, same as the task and streaming
                # paths: callers dispatch on framework exception types
                # (e.g. the handle retries ReplicaDrainingError from
                # stream_next during a live-migration drain).
                err = e
            else:
                err = rexc.ActorError.from_exception(
                    e, name, pid=os.getpid(), node_id=self.core.node_id)
            try:
                self._store_results(spec, err, is_error=True)
            except Exception:  # noqa: BLE001
                pass
            self._record_event(spec, "FAILED", start_ts, _time.time(),
                               error=repr(e),
                               usage=probe.finish() if probe else None)
            return {"results": [], "error": err}

    async def execute_simple(self, spec: dict) -> dict:
        """Cross-language task entry (ref: the C++ worker API's task
        path, cpp/src/ray/runtime/task/): same execution as push_task
        but the reply is a PLAIN dict of primitives — no dataclasses —
        so non-Python clients with a minimal pickle codec can parse it.
        The result payload is the framed serialization bytes."""
        loop = asyncio.get_running_loop()
        reply = await loop.run_in_executor(self._task_pool, self._execute,
                                           spec)
        err = reply.get("error")
        if err is not None:
            return {"ok": False, "error_repr": repr(err)}
        r = reply["results"][0]
        inline = r.inline
        if inline is None:
            buf = self.core.store.get_buffer(ObjectID(r.oid))
            if buf is None:
                return {"ok": False,
                        "error_repr": "result evicted before reply"}
            try:
                inline = bytes(buf.view)
            finally:
                buf.release()
        return {"ok": True, "payload": inline, "oid": r.oid}

    async def profile_memory(self, duration_s: float = 2.0,
                             top_n: int = 20) -> dict:
        """On-demand heap profiling (ref: dashboard memray profiling,
        reporter/profile_manager.py:186 MemoryProfilingManager — memray
        isn't in this image, so tracemalloc supplies allocation sites):
        traces allocations for `duration_s`, returns top allocation
        sites + total traced bytes."""
        import tracemalloc

        from ray_tpu.util.profiling import HEAP_TRACE_LOCK

        loop = asyncio.get_running_loop()

        def run():
            # Serialized: overlapping windows would stop each other's
            # tracing mid-snapshot (tracemalloc state is process-global).
            with HEAP_TRACE_LOCK:
                return _traced_window()

        def _traced_window():
            started_here = not tracemalloc.is_tracing()
            try:
                if started_here:
                    tracemalloc.start(10)
                before = tracemalloc.take_snapshot()
                import time as _t

                _t.sleep(duration_s)
                after = tracemalloc.take_snapshot()
                stats = after.compare_to(before, "traceback")
                top = []
                for st in stats[:top_n]:
                    frames = [f"{f.filename.rsplit('/', 1)[-1]}:{f.lineno}"
                              for f in list(st.traceback)[-6:]]
                    top.append({"size_diff": st.size_diff,
                                "count_diff": st.count_diff,
                                "stack": ";".join(frames)})
                current, peak = tracemalloc.get_traced_memory()
                return {"top": top, "current_bytes": current,
                        "peak_bytes": peak, "duration_s": duration_s}
            finally:
                if started_here and tracemalloc.is_tracing():
                    tracemalloc.stop()

        return await loop.run_in_executor(None, run)

    async def profile(self, duration_s: float = 2.0,
                      interval_s: float = 0.01) -> dict:
        """On-demand stack sampling of this worker (ref: dashboard
        py-spy profiling, reporter/profile_manager.py:75). Runs on a
        sampler thread, so in-flight task execution keeps going and IS
        what gets sampled."""
        from ray_tpu.util.profiling import profile_here

        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, lambda: profile_here(duration_s, interval_s))

    def running_tasks(self) -> dict:
        """Snapshot of attempts currently occupying executor threads —
        the daemon's hung-task watchdog polls this (and falls back to
        the signal-safe dump path when even this RPC can't be served
        because a task is wedged holding the GIL)."""
        import time as _time

        tasks = [dict(v) for v in list(self._running_info.values())]
        with _progress_lock:
            probes = list(_progress_probes.values())
        for probe in probes:
            try:
                entry = probe()
            except Exception:  # noqa: BLE001
                continue
            if entry:
                tasks.append(dict(entry))
        return {"now": _time.time(), "pid": os.getpid(), "tasks": tasks}

    def ping(self) -> dict:
        return {"ok": True, "pid": os.getpid(),
                "actor_id": self.actor_id}


class _NullSpanCM:
    """Reusable no-op context manager: the tracing-off hot path enters
    it per call, so it must not allocate."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpanCM()

_NONE_PAYLOAD: Optional[bytes] = None


def _none_payload() -> bytes:
    global _NONE_PAYLOAD
    if _NONE_PAYLOAD is None:
        _NONE_PAYLOAD = serialization.dumps(None)
    return _NONE_PAYLOAD


def _mkref(oid: ObjectID, owner: Optional[str] = None):
    from ray_tpu.core.object_ref import ObjectRef

    return ObjectRef(oid, owner, _skip_refcount=True)


def run_worker(args) -> None:
    # Signal-safe stack dumps FIRST — before the daemon can learn this
    # pid: faulthandler on SIGUSR1 writes all-thread tracebacks to a
    # per-pid file in the node's log dir, readable by the daemon even
    # when a task wedges the GIL in native code (the default SIGUSR1
    # disposition would TERMINATE the process, so registration must
    # precede any chance of being signalled).
    if get_config().stack_dump_enabled:
        try:
            from ray_tpu.util.profiling import (
                node_log_dir, register_stack_dump_handler,
                stack_dump_path)

            register_stack_dump_handler(stack_dump_path(
                node_log_dir(args.node_id), os.getpid()))
        except Exception as e:  # noqa: BLE001 diagnosis is best-effort
            logger.warning("stack-dump handler unavailable: %s", e)
    # One event loop for ALL grpc.aio objects in this process (server and
    # clients) — grpc-python's aio poller misbehaves across multiple loops.
    from ray_tpu.core.distributed.rpc import EventLoopThread

    loop_thread = EventLoopThread(name="worker-rpc")
    server = RpcServer("127.0.0.1", 0)
    loop_thread.run(server.start())
    address = server.address

    core = DistributedCoreWorker(
        gcs_address=args.gcs_address,
        node_id=args.node_id,
        daemon_address=args.daemon_address,
        store_dir=args.store_dir,
        job_id="worker",
        is_driver=False,
        worker_address=address,
        loop_thread=loop_thread,
    )
    # User code inside tasks talks to the same core worker.
    from ray_tpu import api

    api._set_global_worker(core)

    service = WorkerService(core, args.worker_id)
    server.add_service("Worker", service)
    from ray_tpu.core.distributed.core_worker import OwnerService

    server.add_service("Owner", OwnerService(core))

    async def register():
        daemon = AsyncRpcClient(args.daemon_address)
        await daemon.call("NodeDaemon", "register_worker",
                          worker_id=args.worker_id, address=address,
                          pid=os.getpid(), timeout=30)
        await daemon.close()

    loop_thread.run(register())
    logger.info("worker %s serving on %s", args.worker_id[:8], address)

    # Fate-share with the daemon: if it stops answering pings, exit
    # (ref: workers fate-share with their raylet). This is a BACKSTOP —
    # the kernel PDEATHSIG chain (daemon → zygote → worker) already
    # covers daemon death on Linux — so the cadence is lazy and the
    # client connection persists: a warm pool of ~1k parked workers
    # must not spend the host's CPU on connect/teardown churn.
    failures = 0
    ping_client = AsyncRpcClient(args.daemon_address)
    # lint: allow-knob -- per-worker bootstrap var set by the spawning daemon, read pre-config
    period = float(os.environ.get("RAY_TPU_WORKER_PING_PERIOD_S", "45"))
    while True:
        threading.Event().wait(period)
        try:
            async def ping():
                await ping_client.call("NodeDaemon", "ping", timeout=5)

            loop_thread.run(ping(), timeout=10)
            failures = 0
        except Exception:  # noqa: BLE001
            failures += 1
            if failures >= 3:
                logger.warning("daemon unreachable; exiting (fate-share)")
                os._exit(1)


def boot_worker(args) -> None:
    """Process body shared by the cold-spawn CLI path (`main`) and the
    zygote fork path (worker_zygote._child_main): everything after the
    per-worker identity (worker_id, env, stdio) is known. `force=True`
    because a forked child inherits the zygote's logging handlers."""
    logging.basicConfig(
        level=logging.INFO, force=True,
        format=f"[worker {args.worker_id[:6]}] %(levelname)s %(message)s")
    # tpu_profiling runtime env (the nsight analogue): trace the whole
    # worker process with the JAX profiler, like `nsys profile` wraps
    # the reference's worker (_private/runtime_env/nsight.py).
    # lint: allow-knob -- per-worker channel set by the runtime-env plugin, not a cluster knob
    trace_dir = os.environ.get("RAY_TPU_JAX_TRACE_DIR")
    if trace_dir:
        try:
            import atexit
            import signal

            import jax

            jax.profiler.start_trace(trace_dir)

            def stop_trace_once(*_sig):
                try:
                    jax.profiler.stop_trace()
                except Exception:  # noqa: BLE001 already stopped
                    pass
                if _sig:  # invoked as a signal handler, not atexit
                    os._exit(0)

            atexit.register(stop_trace_once)
            # The daemon's graceful kill is SIGTERM, which does NOT run
            # atexit — without this the trace never finalizes for
            # daemon-terminated workers (SIGKILL remains unhelpable).
            signal.signal(signal.SIGTERM, stop_trace_once)
        except Exception as e:  # noqa: BLE001 profiling is best-effort
            logging.warning("jax trace capture unavailable: %s", e)
    try:
        run_worker(args)
    except KeyboardInterrupt:
        pass


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--daemon-address", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--store-dir", required=True)
    parser.add_argument("--worker-id", required=True)
    boot_worker(parser.parse_args())


if __name__ == "__main__":
    main()
